"""AOT compilation service (spark_tpu/compile/): cross-session
executable store, structural-key fingerprints, background compile +
hot-swap, plan-history pre-warm, size-bound eviction, and the
compile.background fault matrix.

The fused stage path (and hence all store traffic) only engages on a
plan's SECOND execution in a session — the first run executes blocking
to record the adaptive stats that prove the plan fully traceable — so
every store-facing test collects each query twice per session.

Known XLA:CPU limit: LARGE serialized executables can fail
deserialize_and_load in a fresh process ("Symbols not found"); the
store's contract is that any such entry is a miss AND evicted, never a
crash. These tests keep programs small (verified to round-trip) and
separately pin the corrupt→evict policy.
"""

import contextlib
import glob
import os
import re
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import jax

from spark_tpu import conf as CF
from spark_tpu import faults, metrics
from spark_tpu.compile import store as store_mod
from spark_tpu.compile.service import PlanHistory, _replayable_sql
from spark_tpu.compile.store import (ExecutableStore, clear_process_cache,
                                     stable_plan_fingerprint)

pytestmark = pytest.mark.compile

GOLDEN = "SELECT k, SUM(v) AS s, COUNT(*) AS c FROM {t} GROUP BY k ORDER BY k"


@pytest.fixture(scope="module")
def fact_parquet(tmp_path_factory):
    """Small integer fact table: SUM/COUNT are exact in every tier, so
    chunked-vs-fused results compare with == (byte identity), and the
    fused stage program stays small enough to AOT-round-trip on
    XLA:CPU."""
    rng = np.random.default_rng(7)
    n = 5000
    tbl = pa.table({
        "k": pa.array(rng.integers(0, 8, n), pa.int64()),
        "v": pa.array(rng.integers(0, 1000, n), pa.int64()),
    })
    path = str(tmp_path_factory.mktemp("compile") / "fact.parquet")
    pq.write_table(tbl, path, row_group_size=1000)
    return path


@contextlib.contextmanager
def _session(master=None, **conf):
    """A private session with the given compile conf, restoring
    whatever session was active before (compile tests must not leak
    stores/background flags into the shared suite session)."""
    from spark_tpu.api.session import SparkSession

    prev = SparkSession._active
    SparkSession._reset()
    b = SparkSession.builder.appName("compile-test")
    if master:
        b = b.master(master)
    for key, value in conf.items():
        b = b.config(key, value)
    s = b.getOrCreate()
    try:
        yield s
    finally:
        svc = s.__dict__.get("_compile_service")
        if svc is not None:
            svc.wait_background(timeout=60)
        SparkSession._reset()
        SparkSession._active = prev


def _forget_process_state():
    """Simulate a fresh process: drop both jit stage caches and the
    store's in-process loaded-executable registry, so the next
    execution must go back to disk."""
    from spark_tpu.parallel import executor as EX
    from spark_tpu.physical import planner as PL

    PL._STAGE_CACHE.clear()
    EX._DIST_STAGE_CACHE.clear()
    clear_process_cache()


def _rows(spark, query):
    return [r.asDict() for r in spark.sql(query).collect()]


def _run_twice(spark, path, view="compile_fact"):
    """First run records adaptive stats (blocking), second engages the
    fused stage path and hence the executable store."""
    spark.read.parquet(path).createOrReplaceTempView(view)
    q = GOLDEN.format(t=view)
    out = _rows(spark, q)
    assert _rows(spark, q) == out
    return out


# ---- cross-session executable cache ----------------------------------------


@pytest.mark.timeout(300)
def test_cross_session_cache_hit(fact_parquet, tmp_path):
    """A second session pointed at the same store dir serves its fused
    stage from disk — no trace, no compile — with byte-identical
    results."""
    store_dir = str(tmp_path / "store")
    _forget_process_state()
    metrics.reset_exec_store()
    with _session(**{"spark.tpu.compile.store.dir": store_dir}) as s1:
        rows1 = _run_twice(s1, fact_parquet)
        st1 = metrics.exec_store_stats()
        assert st1["misses"] >= 1 and st1["puts"] >= 1
        assert s1.compile_service.store.stats()["entries"] >= 1

    # fresh session, fresh "process": the only warm state is the disk
    _forget_process_state()
    metrics.reset_exec_store()
    with _session(**{"spark.tpu.compile.store.dir": store_dir}) as s2:
        rows2 = _run_twice(s2, fact_parquet)
        st2 = metrics.exec_store_stats()
        assert st2["hits"] >= 1, f"no store hit in fresh session: {st2}"
        assert st2["corrupt"] == 0
    assert rows2 == rows1


@pytest.mark.timeout(120)
def test_store_disabled_is_legacy(fact_parquet):
    """No compile conf at all → no service, no store traffic, plain
    jit path (zero behavior change)."""
    metrics.reset_exec_store()
    with _session() as s:
        assert s.compile_service is None
        _run_twice(s, fact_parquet)
    st = metrics.exec_store_stats()
    assert st["hits"] == st["misses"] == st["puts"] == 0


# ---- structural-key fingerprint sensitivity --------------------------------


def test_fingerprint_sensitivity():
    """The fingerprint must be stable across calls for identical
    inputs, and MISS on any capacity (arg shape), mesh, platform,
    tier, or adaptive-snapshot change."""
    with _session() as s:
        plan = s.createDataFrame(
            [{"k": i % 3, "v": i} for i in range(10)])._plan
        args = (np.arange(16, dtype=np.int64),)
        base = stable_plan_fingerprint("fused", plan, args)
        assert base == stable_plan_fingerprint("fused", plan, args)

        grown = (np.arange(32, dtype=np.int64),)  # capacity change
        assert stable_plan_fingerprint("fused", plan, grown) != base
        assert stable_plan_fingerprint(
            "fused", plan, args, mesh_size=8) != base
        assert stable_plan_fingerprint(
            "fused", plan, args, platform="tpu") != base
        assert stable_plan_fingerprint("dist", plan, args) != base
        assert stable_plan_fingerprint(
            "fused", plan, args, extra={"stats": 1}) != base


def test_fingerprint_survives_hash_salting(fact_parquet, tmp_path):
    """The digest must not depend on PYTHONHASHSEED (dict/str hash()
    is process-salted): two structurally identical plans built from
    scratch fingerprint identically."""
    with _session() as s:
        s.read.parquet(fact_parquet).createOrReplaceTempView("fp_a")
        s.read.parquet(fact_parquet).createOrReplaceTempView("fp_b")
        q = GOLDEN.format(t="fp_a")
        p1 = s.sql(q)._plan
        p2 = s.sql(q)._plan
        args = (np.arange(8, dtype=np.int64),)
        assert stable_plan_fingerprint("fused", p1, args) == \
            stable_plan_fingerprint("fused", p2, args)


# ---- background compile + hot-swap byte identity ---------------------------


@pytest.mark.timeout(480)
@pytest.mark.parametrize("master", [None, "mesh[2]", "mesh[8]"],
                         ids=["dev1", "dev2", "dev8"])
def test_hot_swap_byte_identity(fact_parquet, master):
    """The three-way invariant on every device count: fused-only,
    chunked-while-compiling, and post-swap executions of one query all
    return byte-identical rows; the first request is chunk-served and
    the swap happens exactly once."""
    view = "swap_fact"
    q = GOLDEN.format(t=view)
    with _session(master=master) as plain:
        plain.read.parquet(fact_parquet).createOrReplaceTempView(view)
        fused = _rows(plain, q)
        assert _rows(plain, q) == fused  # fused re-run, same bytes

    metrics.reset_exec_store()
    with _session(master=master, **{
            "spark.tpu.compile.background": True,
            "spark.tpu.compile.chunkFirst.budgetBytes": 16384}) as s:
        svc = s.compile_service
        s.read.parquet(fact_parquet).createOrReplaceTempView(view)
        first = _rows(s, q)  # served chunked, compile in background
        assert svc.wait_background(timeout=120)
        after = _rows(s, q)  # swapped to the fused executable
        st = metrics.exec_store_stats()
        assert st["background"] >= 1, "first request was not chunk-served"
        assert st["swaps"] == 1
        assert st["fallbacks"] == 0
        assert svc.status()["background"]["by_status"] == {"ready": 1}
    assert first == fused
    assert after == fused


@pytest.mark.timeout(120)
def test_background_unchunkable_runs_foreground():
    """A plan with no chunkable shape (in-memory relation) has nothing
    to hide the compile behind: it runs foreground, is marked ready,
    and never crashes or double-probes."""
    with _session(**{"spark.tpu.compile.background": True}) as s:
        df = s.createDataFrame([{"k": i % 3, "v": i} for i in range(100)])
        df.createOrReplaceTempView("mem_t")
        q = "SELECT k, SUM(v) AS s FROM mem_t GROUP BY k ORDER BY k"
        rows = _rows(s, q)
        assert _rows(s, q) == rows
        assert s.compile_service.status()["background"]["by_status"] \
            == {"ready": 1}


# ---- fault matrix: compile.background --------------------------------------


@pytest.mark.timeout(300)
@pytest.mark.parametrize("kind", list(faults.KINDS))
def test_background_failure_pins_chunked(fact_parquet, kind):
    """Every failure kind injected into the background compile job
    leaves the plan pinned to the chunked tier: no swap, no crash,
    byte-identical answers on every subsequent request."""
    view = "fault_fact"
    q = GOLDEN.format(t=view)
    with _session() as plain:
        plain.read.parquet(fact_parquet).createOrReplaceTempView(view)
        oracle = _rows(plain, q)

    metrics.reset_exec_store()
    with _session(**{
            "spark.tpu.compile.background": True,
            "spark.tpu.compile.chunkFirst.budgetBytes": 16384,
            "spark.tpu.faultInjection.compile.background":
                f"nth:1:{kind}"}) as s:
        svc = s.compile_service
        faults.reset(s.conf)
        try:
            s.read.parquet(fact_parquet).createOrReplaceTempView(view)
            first = _rows(s, q)
            assert svc.wait_background(timeout=120)
            again = _rows(s, q)  # still chunked: the compile failed
            st = metrics.exec_store_stats()
            assert st["fallbacks"] == 1
            assert st["swaps"] == 0
            assert st["background"] == 2, "both requests chunk-served"
            assert svc.status()["background"]["by_status"] \
                == {"failed": 1}
        finally:
            faults.reset(s.conf)
    assert first == oracle
    assert again == oracle


@pytest.mark.timeout(300)
def test_symbols_not_found_reload_recompiles_silently(
        fact_parquet, tmp_path, monkeypatch):
    """Regression for the XLA:CPU large-program limit (ROADMAP item 1):
    a stored executable whose re-load dies with "Symbols not found"
    must behave exactly like a corrupt entry — evicted from disk and
    recompiled fresh — with the query never seeing the error, and the
    recompiled entry must round-trip once re-loads work again."""
    store_dir = str(tmp_path / "store")
    _forget_process_state()
    metrics.reset_exec_store()
    with _session(**{"spark.tpu.compile.store.dir": store_dir}) as s1:
        rows1 = _run_twice(s1, fact_parquet)
        assert s1.compile_service.store.stats()["entries"] >= 1

    # fresh "process" whose XLA refuses to re-load the serialization
    _forget_process_state()
    metrics.reset_exec_store()
    from jax.experimental import serialize_executable as _se

    def boom(*a, **k):
        raise RuntimeError(
            "Symbols not found: [__xla_cpu_runtime_AllReduce]")

    monkeypatch.setattr(_se, "deserialize_and_load", boom)
    with _session(**{"spark.tpu.compile.store.dir": store_dir}) as s2:
        rows2 = _run_twice(s2, fact_parquet)  # must not raise
        st = metrics.exec_store_stats()
        assert st["corrupt"] >= 1, "failed re-load must read as corrupt"
        assert st["hits"] == 0
        assert st["puts"] >= 1, "recompile must re-populate the store"
    assert rows2 == rows1

    # with real deserialization back, the re-populated entries serve
    monkeypatch.undo()
    _forget_process_state()
    metrics.reset_exec_store()
    with _session(**{"spark.tpu.compile.store.dir": store_dir}) as s3:
        rows3 = _run_twice(s3, fact_parquet)
        st = metrics.exec_store_stats()
        assert st["hits"] >= 1 and st["corrupt"] == 0
    assert rows3 == rows1


@pytest.mark.timeout(120)
def test_corrupt_entry_is_miss_and_evicted(tmp_path):
    """A poisoned serialized executable must read as a miss AND be
    evicted from disk, never wedge a session."""
    store = ExecutableStore(str(tmp_path / "store"), max_bytes=1 << 30)
    args = (np.arange(16, dtype=np.int64),)
    compiled = jax.jit(lambda a: a[0] + 1).lower(args).compile()
    assert store.put("d" * 32, compiled, None, args)

    clear_process_cache()  # force the disk deserialize path
    path = store._entry_path("d" * 32)
    with open(path, "wb") as f:
        f.write(b"not a pickle")
    before = metrics.exec_store_stats()["corrupt"]
    assert store.load("d" * 32, args) is None
    assert metrics.exec_store_stats()["corrupt"] == before + 1
    assert not os.path.exists(path), "corrupt entry must be evicted"
    # subsequent loads are plain misses, not repeated corruption events
    assert store.load("d" * 32, args) is None
    assert metrics.exec_store_stats()["corrupt"] == before + 1


# ---- size bound / LRU eviction ---------------------------------------------


@pytest.mark.timeout(120)
def test_eviction_at_size_bound(tmp_path):
    """When the store exceeds maxBytes the least-recently-used entry
    goes first; a load of the survivor still round-trips."""
    store = ExecutableStore(str(tmp_path / "store"), max_bytes=1 << 30)
    args = (np.arange(16, dtype=np.int64),)

    def put(digest, c):
        compiled = jax.jit(lambda a: a[0] + c).lower(args).compile()
        assert store.put(digest, compiled, None, args)

    put("a" * 32, 1)
    one_entry = store.total_bytes()
    assert one_entry > 0
    time.sleep(0.05)  # separate mtimes for LRU ordering
    store.max_bytes = int(one_entry * 1.5)
    before = metrics.exec_store_stats()["evictions"]
    put("b" * 32, 2)  # put runs enforce_budget: 2 entries > 1.5x one
    assert metrics.exec_store_stats()["evictions"] >= before + 1
    assert not os.path.exists(store._entry_path("a" * 32))
    assert os.path.exists(store._entry_path("b" * 32))
    assert store.stats()["entries"] == 1
    assert store.total_bytes() <= store.max_bytes

    clear_process_cache()
    entry = store.load("b" * 32, args)
    assert entry is not None
    out = entry["compiled"](args)
    np.testing.assert_array_equal(np.asarray(out), np.arange(16) + 2)


# ---- plan history + pre-warm -----------------------------------------------


def test_plan_history_journal_and_compaction(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    h = PlanHistory(path, max_entries=4)
    for i in range(10):
        h.note(f"fp{i % 5}", sql=f"SELECT {i % 5}")
    # reloaded history aggregates counts and keeps most-frequent-first
    h2 = PlanHistory(path, max_entries=4)
    top = h2.top(3)
    assert len(top) == 3
    counts = [n for _fp, _sql, n in top]
    assert counts == sorted(counts, reverse=True)
    # compaction bounds the on-disk journal near maxEntries lines
    with open(path) as f:
        assert len(f.readlines()) <= 2 * 4 + 1

    assert _replayable_sql("SELECT 1") == "SELECT 1"
    assert _replayable_sql("  with t as (select 1) select * from t")
    assert _replayable_sql("CREATE VIEW v AS SELECT 1") is None
    assert _replayable_sql(None) is None


@pytest.mark.timeout(300)
def test_prewarm_from_history(fact_parquet, tmp_path):
    """Queries served in one session are replayed most-frequent-first
    by prewarm() in the next: the stage caches, executable store, and
    admission's measured-bytes table are hot before the first client
    query."""
    store_dir = str(tmp_path / "store")
    view = "warm_fact"
    hot = GOLDEN.format(t=view)
    cold = f"SELECT COUNT(*) AS c FROM {view}"
    _forget_process_state()
    with _session(**{"spark.tpu.compile.store.dir": store_dir}) as s1:
        s1.read.parquet(fact_parquet).createOrReplaceTempView(view)
        _rows(s1, hot)
        _rows(s1, hot)
        _rows(s1, cold)
        svc1 = s1.compile_service
        assert svc1.history is not None and svc1.history.size() >= 2
    assert os.path.exists(os.path.join(store_dir, "plan_history.jsonl"))

    _forget_process_state()
    metrics.reset_exec_store()
    with _session(**{"spark.tpu.compile.store.dir": store_dir}) as s2:
        s2.read.parquet(fact_parquet).createOrReplaceTempView(view)
        report = s2.compile_service.prewarm(
            block=True, budget_s=120.0, max_queries=8)
        assert report is not None and not report["errors"]
        replayed = report["replayed"]
        assert len(replayed) == 2
        # most-frequent-first: the twice-served query replays first
        assert replayed[0]["count"] >= replayed[1]["count"]
        assert metrics.exec_store_stats()["prewarmed"] == 2
        status = s2.compile_service.status()
        assert status["prewarm"] is report
        assert status["history"]["entries"] >= 2


@pytest.mark.timeout(120)
def test_prewarm_time_budget_skips(fact_parquet, tmp_path):
    """A zero time budget replays nothing and records why — the
    skipped marks name the budget, mirroring bench's phase-skip
    contract."""
    store_dir = str(tmp_path / "store")
    with _session(**{"spark.tpu.compile.store.dir": store_dir}) as s:
        s.read.parquet(fact_parquet).createOrReplaceTempView("budget_t")
        _rows(s, "SELECT COUNT(*) AS c FROM budget_t")
        report = s.compile_service.prewarm(block=True, budget_s=0.0,
                                           max_queries=8)
        assert report["replayed"] == []
        assert any(e["reason"] == "time budget"
                   for e in report["skipped"])


# ---- conf hygiene -----------------------------------------------------------


def test_all_compile_conf_keys_declared():
    """Every spark.tpu.compile.* key referenced anywhere in the source
    is registered in conf.py with a default and a docstring."""
    root = os.path.join(os.path.dirname(__file__), "..", "spark_tpu")
    used = set()
    for path in glob.glob(os.path.join(root, "**", "*.py"),
                          recursive=True):
        with open(path) as f:
            used.update(re.findall(r"spark\.tpu\.compile(?:\.\w+)+",
                                   f.read()))
    assert used, "no spark.tpu.compile.* keys found in source"
    for key in used:
        assert key in CF._REGISTRY, f"{key} not registered in conf.py"
        entry = CF._REGISTRY[key]
        assert entry.doc and len(entry.doc) > 20, f"{key} lacks a doc"
        assert entry.default is not None, f"{key} lacks a default"
    assert "spark.tpu.faultInjection.compile.background" in CF._REGISTRY

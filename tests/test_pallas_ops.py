"""Pallas kernel parity (spark_tpu/ops/pallas_agg.py) — interpret mode
on CPU against a numpy oracle; the same kernel runs compiled on TPU."""

import numpy as np
import pytest

from spark_tpu.ops import pallas_available, pallas_seg_sum


@pytest.mark.parametrize("n,k", [(100, 4), (8192, 16), (20000, 128),
                                 (5, 2)])
def test_seg_sum_matches_numpy(rng, n, k):
    data = rng.normal(size=n).astype(np.float32)
    seg = rng.integers(0, k, n).astype(np.int32)
    mask = rng.random(n) < 0.8
    got = np.asarray(pallas_seg_sum(data, seg, mask, k, interpret=True))
    want = np.zeros(k, np.float32)
    np.add.at(want, seg[mask], data[mask])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_seg_sum_all_masked(rng):
    data = rng.normal(size=300).astype(np.float32)
    seg = np.zeros(300, np.int32)
    got = np.asarray(pallas_seg_sum(
        data, seg, np.zeros(300, bool), 3, interpret=True))
    assert (got == 0).all()


def test_seg_sum_counts(rng):
    """count = sum of the mask itself (how the engine derives counts)."""
    n, k = 4096, 7
    seg = rng.integers(0, k, n).astype(np.int32)
    mask = rng.random(n) < 0.5
    got = np.asarray(pallas_seg_sum(
        mask.astype(np.float32), seg, mask, k, interpret=True))
    want = np.bincount(seg[mask], minlength=k).astype(np.float32)
    np.testing.assert_array_equal(got, want)


def test_availability_gate():
    assert not pallas_available(np.float64, 16, platform="tpu")
    assert not pallas_available(np.float32, 1, platform="tpu")
    assert not pallas_available(np.float32, 100000, platform="tpu")
    assert pallas_available(np.float32, 16, platform="tpu")
    assert not pallas_available(np.float32, 16, platform="cpu")


def test_engine_seg_kernels_take_pallas_path(rng, monkeypatch):
    """seg_sum/seg_count route 64 < K <= 1024 unsorted f32 aggregations
    through the Pallas kernel (SPARK_TPU_PALLAS=force -> interpret on
    CPU) and agree with the scatter path."""
    import jax.numpy as jnp

    from spark_tpu.physical.kernels import seg_count, seg_sum

    n, k = 6000, 100
    data = jnp.asarray(rng.normal(size=n).astype(np.float32))
    seg = jnp.asarray(rng.integers(0, k, n))
    mask = jnp.asarray(rng.random(n) < 0.7)

    base_sum = np.asarray(seg_sum(data, seg, mask, k))
    base_cnt = np.asarray(seg_count(seg, mask, k))
    monkeypatch.setenv("SPARK_TPU_PALLAS", "force")
    got_sum = np.asarray(seg_sum(data, seg, mask, k))
    got_cnt = np.asarray(seg_count(seg, mask, k))
    np.testing.assert_allclose(got_sum, base_sum, rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(got_cnt, base_cnt)
    assert got_cnt.dtype == np.int64

"""End-to-end query tracing (spark_tpu/trace/): hierarchical spans,
cross-replica context propagation, Perfetto export, and the overhead
guard.

Covers the PR-11 acceptance scenarios: a q3-shaped plan produces a
well-formed span tree (single root, no orphans); one trace through a
2-replica fleet — including the 429-shed re-dispatch path — shares one
trace_id end to end and renders as valid Chrome trace-event JSON;
results are byte-identical with tracing on/off/sampled; sampling is
honored; and always-on tracing stays under the 3% overhead budget.
"""

import json
import statistics
import time

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_tpu import conf as CF
from spark_tpu import history, metrics, trace, tracing
from spark_tpu.conf import RuntimeConf
from spark_tpu.connect.server import Client, ConnectServer
from spark_tpu.scheduler import QueryScheduler
from spark_tpu.serve import FederationRouter, serve_fleet

pytestmark = [pytest.mark.trace, pytest.mark.timeout(120)]


@pytest.fixture
def trace_conf(spark):
    """Trace-conf sandbox: spark.tpu.trace.* overrides set inside the
    test are unset afterwards (tracing reverts to always-on)."""
    yield spark.conf
    for k in list(spark.conf._overrides):
        if k.startswith("spark.tpu.trace"):
            spark.conf.unset(k)


def _write_parquet(path, nrows=64, nkeys=4):
    t = pa.table({
        "k": [i % nkeys for i in range(nrows)],
        "v": [float(i) * 0.5 for i in range(nrows)]})
    pq.write_table(t, str(path))
    return str(path)


def _ipc_bytes(table: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def _spans(evs):
    return [e for e in evs if e.get("kind") == "span"]


def _roots(spans):
    ids = {e.get("span_id") for e in spans}
    return [e for e in spans if e.get("parent_id") is None
            or e.get("parent_id") not in ids]


# ---- registration / satellites ---------------------------------------------


def test_trace_conf_keys_registered():
    for key in ("spark.tpu.trace.enabled",
                "spark.tpu.trace.sampleRatio"):
        assert CF.is_registered(key), key


def test_trace_marker_gets_deadlock_guard(request):
    assert request.node.get_closest_marker("timeout") is not None


def test_span_names_registry():
    assert trace.SPAN_NAMES
    for name in ("router.dispatch", "connect.request", "scheduler.run",
                 "query.execute", "stage.run", "stage.device",
                 "pipeline.decode", "pipeline.transfer", "fault.retry"):
        assert name in trace.SPAN_NAMES, name


def test_header_roundtrip_and_malformed_dropped():
    ctx = trace.SpanContext("ab12" * 4, "cd34" * 2, None, True)
    got = trace.from_header(ctx.header())
    assert got is not None
    assert got.trace_id == ctx.trace_id
    assert got.span_id == ctx.span_id
    assert got.sampled is True
    # a remote parent arrives with no local parent_id
    assert got.parent_id is None
    for bad in (None, "", "zz", "a-b", "a-b-c-d", "xyz!-12-1",
                "--1", "ab12-"):
        assert trace.from_header(bad) is None, bad


# ---- span-tree well-formedness ---------------------------------------------


def test_span_tree_well_formed_multi_stage_plan(spark, tmp_path):
    """A q3-shaped plan (join + aggregate + sort: several stages, an
    exchange) produces ONE trace whose span tree has exactly one root,
    no orphaned parent_ids, and per-stage spans."""
    _write_parquet(tmp_path / "tr_a.parquet", 96, 6)
    _write_parquet(tmp_path / "tr_b.parquet", 48, 6)
    spark.read.parquet(str(tmp_path / "tr_a.parquet")) \
        .createOrReplaceTempView("tr_a")
    spark.read.parquet(str(tmp_path / "tr_b.parquet")) \
        .createOrReplaceTempView("tr_b")
    rows = spark.sql(
        "SELECT a.k, SUM(a.v + b.v) AS s FROM tr_a a "
        "JOIN tr_b b ON a.k = b.k GROUP BY a.k ORDER BY s").collect()
    assert rows
    evs = metrics.last_query()
    spans = _spans(evs)
    assert spans, "tracing is on by default — spans must be recorded"
    tids = {e.get("trace_id") for e in spans}
    assert len(tids) == 1
    roots = _roots(spans)
    assert len(roots) == 1, [r.get("name") for r in roots]
    # no orphans: every non-root parent_id is a recorded span
    ids = {e.get("span_id") for e in spans}
    for e in spans:
        if e is not roots[0]:
            assert e.get("parent_id") in ids, e
    names = {e.get("name") for e in spans}
    assert "query.execute" in names
    assert "stage.run" in names
    # flat events (stage, exchange) are stamped with the same trace id
    stages = [e for e in evs if e.get("kind") == "stage"]
    assert stages
    assert all(e.get("trace_id") == next(iter(tids)) for e in stages)


def test_breakdown_components_sum_to_wall(spark, tmp_path):
    _write_parquet(tmp_path / "tr_bd.parquet", 64, 4)
    spark.read.parquet(str(tmp_path / "tr_bd.parquet")) \
        .createOrReplaceTempView("tr_bd")
    spark.sql("SELECT k, SUM(v) FROM tr_bd GROUP BY k").collect()
    bd = tracing.trace_breakdown()
    assert bd["wall_ms"] > 0
    total = (bd["queue_ms"] + bd["device_ms"] + bd["transfer_ms"]
             + bd["host_ms"])
    # host_ms is the remainder by construction: the split sums to wall
    # well inside the 10% acceptance bound
    assert abs(total - bd["wall_ms"]) <= max(0.1 * bd["wall_ms"], 1.0)
    assert tracing.format_trace().startswith("trace ")


def test_chrome_trace_valid_json(spark, tmp_path):
    _write_parquet(tmp_path / "tr_ct.parquet", 64, 4)
    spark.read.parquet(str(tmp_path / "tr_ct.parquet")) \
        .createOrReplaceTempView("tr_ct")
    spark.sql("SELECT k, SUM(v) FROM tr_ct GROUP BY k").collect()
    evs = metrics.last_query()
    tid = next(e["trace_id"] for e in _spans(evs))
    doc = history.chrome_trace(metrics.query_events(tid))
    blob = json.dumps(doc)  # must serialize
    assert json.loads(blob)["displayTimeUnit"] == "ms"
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert xs
    for e in xs:
        assert e["ts"] >= 0 and e["dur"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
    assert {e["name"] for e in xs} >= {"query.execute", "stage.run"}


# ---- fleet propagation ------------------------------------------------------


def test_fleet_propagation_two_replicas(spark, tmp_path):
    """One trace spans client -> router -> replica -> scheduler ->
    stages, and GET /trace/<id> through the router renders it."""
    _write_parquet(tmp_path / "tr_fl.parquet", 64, 4)
    spark.read.parquet(str(tmp_path / "tr_fl.parquet")) \
        .createOrReplaceTempView("tr_fl")
    fleet = serve_fleet(spark, replicas=2)
    try:
        c = Client(fleet.url, timeout=60)
        rows = c.sql("SELECT k, SUM(v) FROM tr_fl GROUP BY k")
        assert rows.num_rows
        assert c.last_trace_id
        spans = _spans(metrics.query_events(c.last_trace_id))
        names = {e.get("name") for e in spans}
        assert names >= {"connect.client", "router.dispatch",
                         "router.forward", "connect.request",
                         "scheduler.run", "query.execute", "stage.run"}
        roots = _roots(spans)
        assert len(roots) == 1
        assert roots[0]["name"] == "connect.client"
        # the Perfetto export fetched over HTTP covers the whole path
        doc = c.trace()
        xs = {e["name"] for e in doc["traceEvents"]
              if e.get("ph") == "X"}
        assert xs >= {"router.dispatch", "connect.request",
                      "scheduler.run", "stage.run"}
    finally:
        fleet.stop()


def test_shed_redispatch_shares_one_trace(spark, tmp_path):
    """A 429-shed re-dispatch stays in ONE trace: both forward
    attempts (the saturated replica and the one that served) appear as
    router.forward spans under the same trace_id."""
    import urllib.request

    _write_parquet(tmp_path / "tr_sh.parquet", 48, 4)
    spark.read.parquet(str(tmp_path / "tr_sh.parquet")) \
        .createOrReplaceTempView("tr_sh")
    full = ConnectServer(
        spark, port=0, replica_id="full",
        scheduler=QueryScheduler(conf=RuntimeConf(
            {"spark.tpu.scheduler.queueDepth": 0}))).start()
    ok = ConnectServer(spark, port=0, replica_id="ok").start()
    router = FederationRouter([full, ok], conf=spark.conf).start()
    try:
        req = urllib.request.Request(
            router.url + "/sql",
            data=json.dumps(
                {"query": "SELECT k FROM tr_sh WHERE k > 0"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.status == 200
            tid = resp.headers.get("X-SparkTpu-Trace-Id")
        assert tid
        evs = metrics.query_events(tid)
        forwards = [e for e in _spans(evs)
                    if e.get("name") == "router.forward"]
        tried = {e.get("replica") for e in forwards}
        assert "ok" in tried
        if "full" in tried:  # round-robin picked the saturated one 1st
            assert len(forwards) >= 2
            sheds = [e for e in evs if e.get("kind") == "serve"
                     and e.get("phase") == "shed"]
            assert sheds and all(e.get("trace_id") == tid
                                 for e in sheds)
    finally:
        router.stop()
        full.stop()
        ok.stop()


# ---- byte identity / sampling / overhead ------------------------------------


def test_on_off_sweep_byte_identity(spark, tmp_path, trace_conf):
    """Tracing never touches data: every cell of the on/off/sampled
    sweep serializes the identical arrow stream."""
    _write_parquet(tmp_path / "tr_bi.parquet", 96, 6)
    spark.read.parquet(str(tmp_path / "tr_bi.parquet")) \
        .createOrReplaceTempView("tr_bi")

    def run():
        return _ipc_bytes(spark.sql(
            "SELECT k, SUM(v) AS s FROM tr_bi GROUP BY k ORDER BY k"
        ).toArrow())

    ref = run()
    for enabled, ratio in ((True, 1.0), (True, 0.5), (True, 0.0),
                           (False, 1.0)):
        trace_conf.set("spark.tpu.trace.enabled", enabled)
        trace_conf.set("spark.tpu.trace.sampleRatio", ratio)
        assert run() == ref, (enabled, ratio)


def test_sampling_honored(spark, tmp_path, trace_conf):
    _write_parquet(tmp_path / "tr_sa.parquet", 64, 4)
    spark.read.parquet(str(tmp_path / "tr_sa.parquet")) \
        .createOrReplaceTempView("tr_sa")

    def run_and_spans(q):
        spark.sql(q).collect()
        return _spans(metrics.last_query())

    trace_conf.set("spark.tpu.trace.sampleRatio", 0.0)
    assert run_and_spans(
        "SELECT k, SUM(v) FROM tr_sa GROUP BY k") == []
    trace_conf.set("spark.tpu.trace.sampleRatio", 1.0)
    assert run_and_spans(
        "SELECT k, SUM(v), COUNT(*) FROM tr_sa GROUP BY k")
    trace_conf.set("spark.tpu.trace.enabled", False)
    assert run_and_spans(
        "SELECT k, MAX(v) FROM tr_sa GROUP BY k") == []


def test_overhead_under_three_percent(spark, tmp_path, trace_conf):
    """Always-on tracing costs <3% on a warm q1-shaped query
    (median-of-alternating-runs; small absolute slack absorbs timer
    noise on runs this short)."""
    _write_parquet(tmp_path / "tr_oh.parquet", 256, 8)
    spark.read.parquet(str(tmp_path / "tr_oh.parquet")) \
        .createOrReplaceTempView("tr_oh")
    q = ("SELECT k, SUM(v) AS s, AVG(v) AS a, COUNT(*) AS n "
         "FROM tr_oh WHERE v >= 0 GROUP BY k ORDER BY k")
    spark.sql(q).collect()  # warm: compile once, outside the clock
    on, off = [], []
    for _ in range(5):
        for enabled, sink in ((True, on), (False, off)):
            trace_conf.set("spark.tpu.trace.enabled", enabled)
            t0 = time.perf_counter()
            spark.sql(q).collect()
            sink.append(time.perf_counter() - t0)
    med_on = statistics.median(on)
    med_off = statistics.median(off)
    assert med_on <= med_off * 1.03 + 0.010, (med_on, med_off)

"""Static plan analysis (spark_tpu/analysis/) + the invariant linter
(tools/lint_invariants.py).

Coverage contract (the analyzer's acceptance bar):

- every TPC-H query analyzes with ZERO error-level diagnostics — the
  level=error submit gate must never reject a legitimate query (no
  false positives),
- seeded defects are each caught with their own distinct code:
  data-dependent shape literal -> PLAN-RECOMPILE-SHAPE, float64 leak
  -> PLAN-DTYPE-F64, float-Sum skew split -> PLAN-MERGE-FLOATSUM,
- the shared legality rules agree with the executor/AggSpec behavior
  they replaced,
- conf.set of an unregistered key follows spark.tpu.analysis.level
  (off: stored, warn: warning, error: KeyError),
- the invariant linter is clean on this tree and each of its four
  rules actually fires on a seeded violation.
"""

import ast
import json
import os
import sys
import urllib.request

import numpy as np
import pandas as pd
import pytest

from spark_tpu import analysis
from spark_tpu import conf as CF
from spark_tpu.analysis import legality, oracle
from spark_tpu.expr import expressions as E
from spark_tpu.tpch.gen import generate_tables, register_views
from spark_tpu.tpch.queries import QUERIES

pytestmark = pytest.mark.analysis

SF = 0.01

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "tools"))
import lint_invariants  # noqa: E402


@pytest.fixture(scope="module")
def tpch(spark):
    tables = generate_tables(SF, seed=99)
    register_views(spark, tables)
    return spark


@pytest.fixture()
def analysis_conf(spark):
    """Restore the analysis confs the test mutates."""
    keys = (CF.ANALYSIS_LEVEL.key, CF.ANALYSIS_ERROR_CODES.key,
            CF.ANALYSIS_DIVERGENCE_FACTOR.key)
    try:
        yield spark.conf
    finally:
        for k in keys:
            spark.conf.unset(k)


# ---- TPC-H: zero false positives at the error gate --------------------------


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_analyzes_with_zero_errors(tpch, qnum):
    spark = tpch
    df = spark.sql(QUERIES[qnum])  # lazy: nothing executes
    report = analysis.analyze(df._plan, spark.conf)
    assert report.node_count > 0
    assert report.peak_bytes > 0
    assert "PLAN-ANALYZE-FAIL" not in report.codes(), report.format()
    assert not report.errors(), report.format()


def test_tpch_error_gate_admits_all_queries(tpch, analysis_conf):
    analysis_conf.set(CF.ANALYSIS_LEVEL.key, "error")
    for qnum in sorted(QUERIES):
        df = tpch.sql(QUERIES[qnum])
        report = analysis.maybe_gate(df._plan, analysis_conf)
        assert report is not None, f"q{qnum}: gate did not run"


# ---- seeded defects: three distinct codes -----------------------------------


def test_seeded_shape_literal_flagged(spark):
    # a data-dependent row count baked into the plan SHAPE: every
    # distinct bound re-traces and recompiles
    df = spark.range(0, 12345)
    report = analysis.analyze(df._plan, spark.conf)
    assert "PLAN-RECOMPILE-SHAPE" in report.codes(), report.format()
    assert not report.fingerprint_stable
    d = next(d for d in report.diagnostics
             if d.code == "PLAN-RECOMPILE-SHAPE")
    assert "Range" in d.node  # names the offending node


def test_seeded_f64_leak_flagged(spark):
    # float64 literal widening integral arithmetic
    df = spark.range(0, 64).selectExpr("id * 1.5 AS x")
    report = analysis.analyze(df._plan, spark.conf)
    assert "PLAN-DTYPE-F64" in report.codes(), report.format()


def test_seeded_float_sum_skew_split_flagged(spark):
    from spark_tpu.api import functions as F

    pdf = pd.DataFrame({"k": np.arange(64) % 4,
                        "v": np.linspace(0.0, 1.0, 64)})
    df = spark.createDataFrame(pdf).groupBy("k").agg(F.sum("v"))
    report = analysis.analyze(df._plan, spark.conf,
                              intent="skew_split")
    assert "PLAN-MERGE-FLOATSUM" in report.codes(), report.format()
    # error-level BECAUSE the declared intent makes it fatal
    assert any(d.code == "PLAN-MERGE-FLOATSUM" and d.level == "error"
               for d in report.diagnostics)
    # ...but merely executing the same plan is legitimate
    relaxed = analysis.analyze(df._plan, spark.conf)
    assert not relaxed.errors(), relaxed.format()


def test_seeded_defect_codes_are_distinct():
    codes = {"PLAN-RECOMPILE-SHAPE", "PLAN-DTYPE-F64",
             "PLAN-MERGE-FLOATSUM"}
    assert len(codes) == 3


# ---- gate behavior ----------------------------------------------------------


def test_gate_off_by_default(spark):
    assert spark.conf.get(CF.ANALYSIS_LEVEL) == "off"
    assert analysis.maybe_gate(spark.range(0, 8)._plan,
                               spark.conf) is None


def test_gate_error_codes_escalation_rejects_collect(spark,
                                                     analysis_conf):
    analysis_conf.set(CF.ANALYSIS_LEVEL.key, "error")
    analysis_conf.set(CF.ANALYSIS_ERROR_CODES.key,
                      "PLAN-RECOMPILE-SHAPE")
    df = spark.range(0, 999)
    with pytest.raises(analysis.PlanAnalysisError) as ei:
        df.collect()
    assert any(d.code == "PLAN-RECOMPILE-SHAPE" for d in ei.value.errors)
    assert ei.value.report.node_count > 0
    # same query at level=warn executes fine
    analysis_conf.set(CF.ANALYSIS_LEVEL.key, "warn")
    assert len(df.collect()) == 999


def test_gate_records_metrics(spark, analysis_conf):
    from spark_tpu import metrics

    before = metrics.analysis_stats()
    analysis.analyze(spark.range(0, 16)._plan, spark.conf)
    after = metrics.analysis_stats()
    assert after["runs"] == before["runs"] + 1
    assert "analysis.elapsed_ms" in metrics.gauges()


# ---- explain("lint") --------------------------------------------------------


def test_explain_lint_mode(spark, capsys):
    spark.range(0, 32).explain(mode="lint")
    out = capsys.readouterr().out
    assert "== Plan Analysis ==" in out
    assert "PLAN-RECOMPILE-SHAPE" in out


# ---- conf: unregistered keys follow the analysis level ----------------------


def test_conf_set_unregistered_key_levels():
    import warnings

    conf = CF.RuntimeConf()
    # off (default): stored silently, discoverable via entries()
    conf.set("spark.tpu.bogus.key", "1")
    assert conf.entries()["spark.tpu.bogus.key"] == "1"
    conf = CF.RuntimeConf({CF.ANALYSIS_LEVEL.key: "warn"})
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        conf.set("spark.tpu.bogus.key", "1")
    assert any("spark.tpu.bogus.key" in str(x.message) for x in w)
    conf = CF.RuntimeConf({CF.ANALYSIS_LEVEL.key: "error"})
    with pytest.raises(KeyError):
        conf.set("spark.tpu.bogus.key", "1")


def test_conf_registered_prefix_admits_pool_keys():
    conf = CF.RuntimeConf({CF.ANALYSIS_LEVEL.key: "error"})
    # free-form per-pool keys match the registered prefix
    conf.set("spark.tpu.scheduler.pool.etl.weight", "3")
    assert conf.get("spark.tpu.scheduler.pool.etl.weight") == "3"


# ---- shared legality rules agree with the paths they replaced ---------------


def test_legality_matches_executor_remerge_rule(spark):
    from spark_tpu.api import functions as F

    pdf = pd.DataFrame({"k": np.arange(32) % 4,
                        "i": np.arange(32),
                        "f": np.linspace(0.0, 1.0, 32)})
    base = spark.createDataFrame(pdf)
    ok = base.groupBy("k").agg(F.sum("i"))._plan
    bad = base.groupBy("k").agg(F.sum("f"))._plan
    from spark_tpu.plan import logical as L

    def agg_of(plan):
        return next(n for n in [plan] + list(plan.children())
                    if isinstance(n, L.Aggregate))

    assert legality.remerge_verdict(agg_of(ok)).ok
    v = legality.remerge_verdict(agg_of(bad))
    assert not v.ok and v.code == "PLAN-MERGE-FLOATSUM"


def test_legality_accumulator_verdicts():
    v = legality.accumulator_verdict(E.Count(E.Col("x"), distinct=True))
    assert not v.ok and v.code == "PLAN-ACC-NONMERGEABLE"
    assert legality.accumulator_verdict(E.Sum(E.Col("x"))).ok
    assert legality.accumulator_verdict(E.Avg(E.Col("x"))).ok


def test_aggspec_uses_shared_rule():
    from spark_tpu.plan.incremental import AggSpec

    with pytest.raises(NotImplementedError, match="DISTINCT"):
        AggSpec((E.Col("k"),),
                (E.Alias(E.Count(E.Col("x"), distinct=True), "c"),))


# ---- oracle internals -------------------------------------------------------


def test_oracle_row_width_counts_validity_planes(spark):
    df = spark.range(0, 8)  # single non-nullable int64 column
    est = oracle.infer(df._plan, spark.conf)
    assert est[-1].row_bytes == 8
    assert est[-1].capacity >= 8
    assert est[-1].device_bytes == est[-1].capacity * 8


def test_oracle_capacity_bucket_rounding(spark):
    multiple = int(spark.conf.get(CF.BATCH_CAPACITY_MULTIPLE))
    est = oracle.infer(spark.range(0, multiple + 1)._plan, spark.conf)
    assert est[-1].capacity == 2 * multiple


def test_hazards_stable_plan(spark):
    # a Relation scan with plain column projection has no literals and
    # no shape-bearing scalars: fingerprint-stable
    pdf = pd.DataFrame({"a": np.arange(16), "b": np.arange(16.0)})
    df = spark.createDataFrame(pdf).select("a", "b")
    report = analysis.analyze(df._plan, spark.conf)
    assert report.fingerprint_stable, report.format()


# ---- analyzer overhead ------------------------------------------------------


def test_analyzer_overhead_under_50ms(tpch):
    spark = tpch
    df = spark.sql(QUERIES[1])
    analysis.analyze(df._plan, spark.conf)  # warm imports off the clock
    report = analysis.analyze(df._plan, spark.conf)
    assert report.elapsed_ms < 50.0, \
        f"analyzer took {report.elapsed_ms:.1f} ms on q1 at SF{SF}"


# ---- HTTP surfaces ----------------------------------------------------------


def test_api_v1_lint_endpoint(spark):
    from spark_tpu.ui import StatusServer

    analysis.analyze(spark.range(0, 8)._plan, spark.conf)
    srv = StatusServer(session=spark, port=0)
    try:
        with urllib.request.urlopen(srv.url + "/api/v1/lint",
                                    timeout=10) as r:
            body = json.loads(r.read())
    finally:
        srv.stop()
    assert body["profile"]["totals"]["runs"] >= 1
    assert isinstance(body["recent"], list) and body["recent"]
    assert "diagnostics" in body["recent"][-1]


@pytest.mark.timeout(120)
def test_connect_lint_endpoint(tpch):
    from spark_tpu.connect.server import ConnectServer

    srv = ConnectServer(tpch, port=0).start()
    try:
        req = urllib.request.Request(
            f"http://{srv.host}:{srv.port}/lint",
            data=json.dumps(
                {"query": "SELECT l_orderkey FROM lineitem "
                          "WHERE l_quantity > 10"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            body = json.loads(r.read())
    finally:
        srv.stop()
    assert body["node_count"] > 0
    assert body["errors"] == 0


# ---- invariant linter -------------------------------------------------------


def test_lint_invariants_clean_on_tree():
    findings = lint_invariants.run_lint()
    assert findings == [], "\n".join(f.format() for f in findings)


def test_lint_rule_conf_keys_fires():
    tree = ast.parse("conf.get('spark.tpu.not.a.real.key')")
    out = []
    lint_invariants._check_conf_keys(
        tree, "x.py", lint_invariants.DEFAULT_CONFIG, out)
    assert len(out) == 1 and out[0].rule == "conf-keys"


def test_lint_rule_fault_points_fires():
    tree = ast.parse("faults.inject('bogus.point', conf)")
    out = []
    lint_invariants._check_fault_points(tree, "x.py", out)
    assert len(out) == 1 and out[0].rule == "fault-points"
    ok = []
    lint_invariants._check_fault_points(
        ast.parse("faults.inject('connect.request', conf)"), "x.py", ok)
    assert ok == []


def test_lint_rule_fingerprint_purity_fires():
    src = (
        "def stable_plan_key(d):\n"
        "    a = hash(d)\n"
        "    for k, v in d.items():\n"
        "        pass\n"
        "    for k in sorted(d.items()):\n"
        "        pass\n"
        "    return a\n")
    out = []
    lint_invariants._check_fingerprint_purity(
        ast.parse(src), "x.py", [], out)
    rules = [f.message for f in out]
    assert len(out) == 2, rules  # hash() + unsorted .items(); NOT the
    #                              sorted(...) one


def test_lint_rule_metrics_lock_fires():
    src = (
        "import threading\n"
        "_LOCK = threading.Lock()\n"
        "_EVENTS = []\n"
        "def bad(ev):\n"
        "    _EVENTS.append(ev)\n"
        "def good(ev):\n"
        "    with _LOCK:\n"
        "        _EVENTS.append(ev)\n")
    out = []
    lint_invariants._check_metrics_locks(
        ast.parse(src), "x.py", lint_invariants.DEFAULT_CONFIG, out)
    assert len(out) == 1 and out[0].line == 5


def test_lint_cli_exits_zero():
    import subprocess

    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools",
                      "lint_invariants.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout

"""applyInPandasWithState / flatMapGroupsWithState
(spark_tpu/streaming/groups.py; reference:
FlatMapGroupsWithStateExec.scala, pyspark group_ops.py)."""

import pandas as pd
import pyarrow as pa
import pytest

from spark_tpu.streaming import MemoryStream


def _counter(key, pdf, state):
    total = (state.get() if state.exists else 0) + len(pdf)
    state.update(total)
    return pd.DataFrame({"k": [key[0]], "cnt": [total]})


def _start(spark, name, ckpt=None):
    src = MemoryStream(pa.schema([("k", pa.string()),
                                  ("v", pa.int64())]))
    df = spark.readStream.load(src)
    out = df.groupBy("k").applyInPandasWithState(
        _counter, "k string, cnt long", "cnt long", "update")
    w = out.writeStream.outputMode("update").queryName(name)
    if ckpt:
        w = w.option("checkpointLocation", ckpt)
    return src, w.start()


def test_running_count_across_batches(spark):
    src, q = _start(spark, "gs1")
    src.add_data([{"k": "a", "v": 1}, {"k": "b", "v": 2},
                  {"k": "a", "v": 3}])
    q.processAllAvailable()
    rows = {(r["k"], r["cnt"]) for r in spark.table("gs1").collect()}
    assert rows == {("a", 2), ("b", 1)}

    src.add_data([{"k": "a", "v": 9}])
    q.processAllAvailable()
    rows = {(r["k"], r["cnt"]) for r in spark.table("gs1").collect()}
    # update mode appends the new per-batch emissions
    assert ("a", 3) in rows


def test_state_remove(spark):
    def evictor(key, pdf, state):
        if state.exists:
            state.remove()
            return pd.DataFrame({"k": [key[0]], "cnt": [-1]})
        state.update(len(pdf))
        return None

    src = MemoryStream(pa.schema([("k", pa.string()),
                                  ("v", pa.int64())]))
    out = spark.readStream.load(src).groupBy("k").applyInPandasWithState(
        evictor, "k string, cnt long")
    q = out.writeStream.outputMode("append").queryName("gs2").start()
    src.add_data([{"k": "x", "v": 1}])
    q.processAllAvailable()
    assert spark.table("gs2").count() == 0  # first batch: state created
    src.add_data([{"k": "x", "v": 1}])
    q.processAllAvailable()
    rows = [tuple(r.asDict().values())
            for r in spark.table("gs2").collect()]
    assert rows == [("x", -1)]
    # state removed: next batch recreates instead of emitting
    src.add_data([{"k": "x", "v": 1}])
    q.processAllAvailable()
    assert spark.table("gs2").count() == 1


def test_checkpoint_restart_restores_state(spark, tmp_path):
    ckpt = str(tmp_path / "gs")
    src, q = _start(spark, "gs3", ckpt)
    src.add_data([{"k": "a", "v": 1}, {"k": "a", "v": 2}])
    q.processAllAvailable()
    q.stop()

    df = spark.readStream.load(src).groupBy("k").applyInPandasWithState(
        _counter, "k string, cnt long")
    q2 = df.writeStream.outputMode("update").queryName("gs3b") \
        .option("checkpointLocation", ckpt).start()
    src.add_data([{"k": "a", "v": 5}])
    q2.processAllAvailable()
    rows = {(r["k"], r["cnt"]) for r in spark.table("gs3b").collect()}
    assert ("a", 3) in rows  # 2 from restored state + 1 new


def test_tuple_valued_state_roundtrips(spark, tmp_path):
    """A user state value that is itself a 2-tuple must survive a
    checkpoint restart intact — the old layout shape-sniffed
    ``(value, deadline)`` and would misread it."""
    def pair_counter(key, pdf, state):
        cnt, tot = state.get() if state.exists else (0, 0)
        cnt, tot = cnt + len(pdf), tot + int(pdf["v"].sum())
        state.update((cnt, tot))
        return pd.DataFrame({"k": [key[0]], "cnt": [cnt], "tot": [tot]})

    ckpt = str(tmp_path / "gs_pair")
    src = MemoryStream(pa.schema([("k", pa.string()),
                                  ("v", pa.int64())]))
    df = spark.readStream.load(src).groupBy("k").applyInPandasWithState(
        pair_counter, "k string, cnt long, tot long")
    q = df.writeStream.outputMode("update").queryName("gsp") \
        .option("checkpointLocation", ckpt).start()
    src.add_data([{"k": "a", "v": 10}, {"k": "a", "v": 20}])
    q.processAllAvailable()
    q.stop()

    df2 = spark.readStream.load(src).groupBy("k").applyInPandasWithState(
        pair_counter, "k string, cnt long, tot long")
    q2 = df2.writeStream.outputMode("update").queryName("gspb") \
        .option("checkpointLocation", ckpt).start()
    src.add_data([{"k": "a", "v": 5}])
    q2.processAllAvailable()
    rows = {(r["k"], r["cnt"], r["tot"])
            for r in spark.table("gspb").collect()}
    assert ("a", 3, 35) in rows


def test_legacy_checkpoint_layouts_load(spark):
    """Versioned payloads coexist with both legacy layouts: the
    untagged (value, deadline) tuple and the pre-timeout bare value."""
    import pickle

    from spark_tpu.streaming.groups import GroupStateQuery

    class _Q:  # borrow only the loader
        _load_states = GroupStateQuery._load_states
        _STATE_TAG = GroupStateQuery._STATE_TAG
        _STATE_VERSION = GroupStateQuery._STATE_VERSION

        def __init__(self, tbl):
            self._tbl = tbl

        class _Store:
            def __init__(self, tbl):
                self._tbl = tbl

            def get(self, version):
                return self._tbl

        @property
        def _store(self):
            return self._Store(self._tbl)

    tbl = pa.table({
        "__key": pa.array([pickle.dumps(("a",)), pickle.dumps(("b",)),
                           pickle.dumps(("c",))], pa.binary()),
        "__state": pa.array([
            pickle.dumps({"__group_state__": 1, "value": 7,
                          "deadline_ms": 123}),     # current
            pickle.dumps((5, None)),                # legacy tuple
            pickle.dumps(42),                       # pre-timeout bare
        ], pa.binary())})
    states = _Q(tbl)._load_states(0)
    assert states[("a",)].get() == 7
    assert states[("a",)]._deadline_ms == 123
    assert states[("b",)].get() == 5
    assert states[("c",)].get() == 42

    # a NEWER format version fails loudly instead of misreading
    tbl2 = pa.table({
        "__key": pa.array([pickle.dumps(("z",))], pa.binary()),
        "__state": pa.array([pickle.dumps(
            {"__group_state__": 99, "value": 1})], pa.binary())})
    with pytest.raises(ValueError, match="newer"):
        _Q(tbl2)._load_states(0)


def test_plan_below_group_runs_on_engine(spark):
    src = MemoryStream(pa.schema([("k", pa.string()),
                                  ("v", pa.int64())]))
    df = spark.readStream.load(src).filter("v > 0") \
        .withColumnRenamed("v", "val")
    out = df.groupBy("k").applyInPandasWithState(
        lambda key, pdf, st: pd.DataFrame(
            {"k": [key[0]], "s": [int(pdf["val"].sum())]}),
        "k string, s long")
    q = out.writeStream.outputMode("append").queryName("gs4").start()
    src.add_data([{"k": "a", "v": -5}, {"k": "a", "v": 3},
                  {"k": "a", "v": 4}])
    q.processAllAvailable()
    rows = [tuple(r.asDict().values())
            for r in spark.table("gs4").collect()]
    assert rows == [("a", 7)]


def test_ddl_schema_parsing():
    from spark_tpu import types as T
    from spark_tpu.types import parse_ddl_schema

    s = parse_ddl_schema("a bigint, b string, c double, d date")
    assert s.names == ("a", "b", "c", "d")
    assert isinstance(s.field("a").dtype, T.Int64Type)
    assert isinstance(s.field("c").dtype, T.Float64Type)
    with pytest.raises(ValueError):
        parse_ddl_schema("bad")

"""HBM-resident columnar storage: MemoryStore + UnifiedMemoryManager
(spark_tpu/storage/) — byte-accounted LRU caching, pinning, unified
storage/execution budget sharing with the scheduler's admission
control, auto-cache promotion of hot scans, and the bounded jit stage
caches."""

import glob
import os
import re
import threading
import time

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_tpu import conf as CF
from spark_tpu import metrics
from spark_tpu.storage import (LruDict, MemoryStore, UnifiedMemoryManager,
                               pin_scope)

pytestmark = pytest.mark.storage


class FakeBatch:
    """Store payload with an exact byte size (store tests need sizes,
    not real device arrays)."""

    def __init__(self, nbytes: int):
        self._n = int(nbytes)

    def device_nbytes(self) -> int:
        return self._n


def _mgr(budget, min_storage=0, max_storage=None):
    return UnifiedMemoryManager(budget, min_storage_bytes=min_storage,
                                max_storage_bytes=max_storage)


# ---- store basics -----------------------------------------------------------


def test_put_get_accounting():
    m = _mgr(1000)
    s = MemoryStore(m)
    assert s.put("a", FakeBatch(300))
    assert s.bytes_used() == 300
    assert s.get("a") is not None
    assert s.get("zzz") is None
    st = s.stats()
    assert st["hits"] == 1 and st["misses"] == 1
    assert st["hit_bytes"] == 300
    assert m.snapshot()["storage_bytes"] == 300


def test_storage_lru_eviction_order():
    m = _mgr(1000)
    s = MemoryStore(m)
    s.put("a", FakeBatch(400))
    s.put("b", FakeBatch(400))
    s.get("a")  # touch: b becomes LRU
    assert s.put("c", FakeBatch(400))
    assert "b" not in s and "a" in s and "c" in s
    assert s.bytes_used() <= 1000
    assert s.stats()["evictions"] == 1


def test_put_larger_than_budget_rejected():
    m = _mgr(1000)
    s = MemoryStore(m)
    assert not s.put("huge", FakeBatch(2000))
    assert s.bytes_used() == 0
    assert s.stats()["rejected_puts"] == 1


def test_max_storage_caps_below_budget():
    m = _mgr(1000, max_storage=500)
    s = MemoryStore(m)
    assert s.put("a", FakeBatch(400))
    assert s.put("b", FakeBatch(400))  # evicts a to stay under 500
    assert "a" not in s
    assert s.bytes_used() <= 500


# ---- unified storage/execution budget ---------------------------------------


def test_execution_evicts_unpinned_storage_to_floor():
    m = _mgr(1000, min_storage=200)
    s = MemoryStore(m)
    s.put("a", FakeBatch(300))
    s.put("b", FakeBatch(300))
    charge = m.acquire_execution(500)  # needs 100 more than free span
    assert charge == 500
    snap = m.snapshot()
    assert snap["in_use_bytes"] + snap["storage_bytes"] <= 1000
    assert s.stats()["evictions"] >= 1
    assert m.evicted_for_execution >= 1
    m.release_execution(charge)


def test_pinned_entries_survive_execution_pressure():
    m = _mgr(1000, min_storage=0)
    s = MemoryStore(m)
    s.put("pinned", FakeBatch(600))
    with pin_scope():
        assert s.get("pinned", pin=True) is not None
        charge = m.acquire_execution(900)
        # pinned entry not evictable: grant is capped, invariant holds
        assert "pinned" in s
        snap = m.snapshot()
        assert snap["in_use_bytes"] + snap["storage_bytes"] <= 1000
        m.release_execution(charge)
    # scope exited: pin released, execution can now reclaim it
    charge = m.acquire_execution(900)
    assert "pinned" not in s
    assert charge == 900
    m.release_execution(charge)


def test_idle_overbudget_query_admits_even_when_storage_full():
    m = _mgr(1000, min_storage=0)
    s = MemoryStore(m)
    with pin_scope():
        s.put("k", FakeBatch(1000), pin=True)
        assert m.fits_execution(5000)  # idle device: always progress
        charge = m.acquire_execution(5000)
        assert charge == 0  # nothing reclaimable: runs ungated
        snap = m.snapshot()
        assert snap["in_use_bytes"] + snap["storage_bytes"] <= 1000
        m.release_execution(charge)


def test_pin_scope_reentrant():
    m = _mgr(1000)
    s = MemoryStore(m)
    s.put("k", FakeBatch(100))
    with pin_scope():
        s.get("k", pin=True)
        with pin_scope():  # inner scope folds into the outer
            s.get("k", pin=True)
        assert s.entries_snapshot()[0]["pins"] == 2  # inner did NOT unpin
    assert s.entries_snapshot()[0]["pins"] == 0


# ---- scheduler integration --------------------------------------------------


@pytest.mark.timeout(60)
def test_eviction_racing_admission_invariant_8_clients():
    """8 workers churn storage puts/pinned gets while the scheduler
    admits/releases execution grants against the SAME unified budget;
    a sampler asserts storage+execution never exceeds it."""
    from spark_tpu.scheduler import QueryScheduler

    conf = CF.RuntimeConf({
        "spark.tpu.scheduler.hbmBudgetBytes": 10_000,
        "spark.tpu.storage.minBytes": 1_000,
        "spark.tpu.storage.maxBytes": 8_000,
        "spark.tpu.scheduler.maxConcurrency": 8,
        "spark.tpu.scheduler.queueDepth": 256,
    })
    sched = QueryScheduler(conf=conf)
    m = sched.admission.manager
    store = MemoryStore(m)
    stop = threading.Event()
    violations = []

    def sampler():
        while not stop.is_set():
            snap = m.snapshot()
            if snap["in_use_bytes"] + snap["storage_bytes"] \
                    > snap["budget_bytes"]:
                violations.append(snap)
            time.sleep(0.0005)

    sampler_t = threading.Thread(target=sampler, daemon=True)
    sampler_t.start()

    def make_run(i):
        def run(t):
            with pin_scope():
                key = ("hot", i % 6)
                if store.get(key, pin=True) is None:
                    store.put(key, FakeBatch(1500), pin=True)
                time.sleep(0.002)
            return i
        return run

    tickets = [sched.submit(make_run(i), description=f"q{i}",
                            est_bytes=(i % 5 + 1) * 1200)
               for i in range(64)]
    results = [t.result(timeout=30) for t in tickets]
    stop.set()
    sampler_t.join(1)
    sched.stop()
    assert results == list(range(64))
    assert not violations, f"budget invariant violated: {violations[:3]}"


def test_session_scheduler_share_manager(spark):
    from spark_tpu.scheduler import QueryScheduler

    sched = QueryScheduler(spark)
    try:
        assert sched.admission.manager is spark.memory_manager
    finally:
        sched.stop()


# ---- session cache manager on the store -------------------------------------


def _write_parquet(tmp_path, name, nrows=256):
    t = pa.table({
        "k": pa.array([i % 7 for i in range(nrows)], pa.int64()),
        "v": pa.array([float(i) for i in range(nrows)], pa.float64()),
    })
    p = os.path.join(str(tmp_path), name)
    pq.write_table(t, p)
    return p


def test_cache_materializes_into_store_and_uncache_releases(spark, tmp_path):
    df = spark.read.parquet(_write_parquet(tmp_path, "t1.parquet"))
    agg = df.groupBy("k").count()
    before = spark.memory_store.bytes_used()
    df.cache()
    r1 = agg.toArrow()
    after = spark.memory_store.bytes_used()
    assert after > before  # cached table is device-resident in the store
    r2 = agg.toArrow()
    assert r2.equals(r1)
    df.unpersist()
    assert spark.memory_store.bytes_used() == before  # bytes released


def test_recompute_after_evict_is_byte_identical(spark, tmp_path):
    df = spark.read.parquet(_write_parquet(tmp_path, "t2.parquet"))
    agg = df.groupBy("k").count()
    df.cache()
    try:
        r1 = agg.toArrow()
        misses0 = spark.memory_store.stats()["misses"]
        # evict everything the store holds (execution-pressure analogue)
        with spark.memory_manager.lock:
            spark.memory_store._evict_locked(1 << 62, floor=0,
                                             reason="execution")
        r2 = agg.toArrow()  # recompute-after-evict: single-flight rerun
        assert r2.equals(r1)
        assert spark.memory_store.stats()["misses"] > misses0
        # the recompute re-populated the store; third run hits
        hits0 = spark.memory_store.stats()["hits"]
        assert agg.toArrow().equals(r1)
        assert spark.memory_store.stats()["hits"] > hits0
    finally:
        df.unpersist()


@pytest.mark.timeout(120)
def test_concurrent_cached_queries_byte_identical_under_eviction(
        spark, tmp_path):
    """Two cached tables that cannot BOTH fit: every read of one may
    evict the other, so 8 client threads continuously race eviction
    against materialization. All results must stay byte-identical."""
    df1 = spark.read.parquet(_write_parquet(tmp_path, "e1.parquet", 512))
    df2 = spark.read.parquet(_write_parquet(tmp_path, "e2.parquet", 512))
    a1, a2 = df1.groupBy("k").count(), df2.groupBy("k").count()
    df1.cache()
    df2.cache()
    base = spark.memory_store.bytes_used()
    ref1 = a1.toArrow()
    one = spark.memory_store.bytes_used() - base
    ref2 = a2.toArrow()
    try:
        # room for ~1.5 entries: the second table's put evicts the first
        spark.conf.set("spark.tpu.storage.maxBytes", max(1, int(one * 1.5)))
        spark.conf.set("spark.tpu.storage.minBytes", 0)
        bad, lock = [], threading.Lock()

        def client(i):
            for _ in range(6):
                agg, ref = (a1, ref1) if i % 2 == 0 else (a2, ref2)
                try:
                    out = agg.toArrow()
                    if not out.equals(ref):
                        with lock:
                            bad.append(f"client{i}: result mismatch")
                except Exception as e:  # noqa: BLE001
                    with lock:
                        bad.append(f"client{i}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not bad, bad[:5]
        snap = spark.memory_manager.snapshot()
        assert snap["storage_bytes"] + snap["in_use_bytes"] \
            <= snap["budget_bytes"]
    finally:
        spark.conf.unset("spark.tpu.storage.maxBytes")
        spark.conf.unset("spark.tpu.storage.minBytes")
        df1.unpersist()
        df2.unpersist()


def test_auto_cache_promotes_hot_scan(spark, tmp_path):
    df = spark.read.parquet(_write_parquet(tmp_path, "hot.parquet"))
    q = df.select("v").filter(df.v >= 0.0)
    entries0 = len(spark.memory_store)
    q.collect()  # read 1: below threshold (default 2)
    q.collect()  # read 2: promoted into the store
    assert len(spark.memory_store) > entries0
    hits0 = spark.memory_store.stats()["hits"]
    r = q.collect()  # read 3: served from the store
    assert spark.memory_store.stats()["hits"] > hits0
    assert len(r) == 256


def test_auto_cache_disabled_by_conf(spark, tmp_path):
    spark.conf.set("spark.tpu.storage.autoCacheThreshold", 0)
    try:
        df = spark.read.parquet(_write_parquet(tmp_path, "cold.parquet"))
        q = df.select("k")
        entries0 = len(spark.memory_store)
        for _ in range(4):
            q.collect()
        assert len(spark.memory_store) == entries0
    finally:
        spark.conf.unset("spark.tpu.storage.autoCacheThreshold")


# ---- bounded jit stage caches -----------------------------------------------


def test_lru_dict_bounded_with_gauge():
    d = LruDict("t_bound", cap=3)
    for i in range(6):
        d[i] = i * 10
    assert len(d) == 3
    assert 0 not in d and 5 in d
    assert d.evictions == 3
    assert metrics.gauges()["jit_cache.t_bound.entries"] == 3
    d.get(3)  # touch
    d[6] = 60
    assert 3 in d and 4 not in d  # LRU, not FIFO


def test_stage_caches_are_bounded_and_conf_driven(spark):
    from spark_tpu.parallel import executor as EX
    from spark_tpu.physical import planner as PL

    assert isinstance(PL._STAGE_CACHE, LruDict)
    assert isinstance(EX._DIST_STAGE_CACHE, LruDict)
    spark.conf.set("spark.tpu.jit.stageCacheEntries", 2)
    try:
        d = LruDict("t_conf", cap_entry=CF.JIT_STAGE_CACHE_ENTRIES)
        for i in range(5):
            d[i] = i
        assert len(d) == 2  # cap read live from the session conf
    finally:
        spark.conf.unset("spark.tpu.jit.stageCacheEntries")


# ---- compile-cache counters + warmup profile --------------------------------


def test_compile_cache_counters_and_warmup_profile():
    from spark_tpu import tracing

    before = metrics.compile_cache_stats()
    metrics.note_compile_cache(True)
    metrics.note_compile_cache(False)
    after = metrics.compile_cache_stats()
    assert after["hits"] == before["hits"] + 1
    assert after["misses"] == before["misses"] + 1
    prof = tracing.warmup_profile([
        {"kind": "stage_compile", "ms": 120.0},
        {"kind": "scan", "decode_ms": 30.0, "transfer_ms": 5.0},
    ])
    assert prof["compile"] == {"count": 1, "total_ms": 120.0}
    assert prof["decode"]["total_ms"] == 30.0
    assert prof["transfer"]["total_ms"] == 5.0
    assert "hits" in prof["compile_cache"]
    assert "compile" in tracing.format_warmup_profile(prof)


def test_instrument_compile_cache_idempotent():
    from spark_tpu.api.session import _instrument_compile_cache

    _instrument_compile_cache()
    _instrument_compile_cache()
    try:
        from jax._src import compilation_cache as _cc
    except Exception:
        return
    fn = getattr(_cc, "get_executable_and_time", None)
    if fn is not None:
        assert getattr(fn, "_spark_tpu_counted", False)
        # double-instrumenting must not stack wrappers
        assert not getattr(getattr(fn, "__wrapped__", None),
                           "_spark_tpu_counted", False)


# ---- storage profile + UI ---------------------------------------------------


def test_storage_profile_rollup(spark):
    from spark_tpu import tracing

    prof = tracing.storage_profile([
        {"kind": "storage", "phase": "hit", "bytes": 100},
        {"kind": "storage", "phase": "hit", "bytes": 50},
        {"kind": "storage", "phase": "evict", "bytes": 100},
    ])
    assert prof["hit"] == {"count": 2, "bytes": 150}
    assert prof["evict"] == {"count": 1, "bytes": 100}
    assert "store" in prof and "memory" in prof  # live session numbers
    txt = tracing.format_storage_profile(prof)
    assert "occupancy" in txt and "hit" in txt


def test_ui_storage_endpoint(spark):
    import json
    import urllib.request

    from spark_tpu.ui import StatusServer

    srv = StatusServer(spark, port=0)
    try:
        with urllib.request.urlopen(f"{srv.url}/api/v1/storage",
                                    timeout=10) as r:
            payload = json.loads(r.read())
        assert set(payload) >= {"store", "memory", "entries"}
        assert payload["memory"]["budget_bytes"] > 0
        with urllib.request.urlopen(f"{srv.url}/api/v1/status",
                                    timeout=10) as r:
            status = json.loads(r.read())
        assert status["storage"] is not None
    finally:
        srv.stop()


# ---- conf hygiene -----------------------------------------------------------


def test_all_storage_conf_keys_declared():
    """Every spark.tpu.storage.* key referenced anywhere in the source
    is registered in conf.py with a default and a docstring."""
    root = os.path.join(os.path.dirname(__file__), "..", "spark_tpu")
    used = set()
    for path in glob.glob(os.path.join(root, "**", "*.py"),
                          recursive=True):
        with open(path) as f:
            used.update(re.findall(r"spark\.tpu\.storage\.\w+",
                                   f.read()))
    assert used, "no spark.tpu.storage.* keys found in source"
    for key in used:
        assert key in CF._REGISTRY, f"{key} not registered in conf.py"
        entry = CF._REGISTRY[key]
        assert entry.doc and len(entry.doc) > 20, f"{key} lacks a doc"
        assert entry.default is not None, f"{key} lacks a default"

"""ROLLUP / CUBE / GROUPING SETS via the Expand analogue (reference:
Analyzer.scala ResolveGroupingAnalytics + execution/ExpandExec.scala:1
+ grouping.scala). sqlite has no grouping sets, so the oracle here is
hand-computed UNION-of-aggregates over the same rows."""

import pyarrow as pa
import pytest

from spark_tpu.api import functions as F

ROWS = [("x", "p", 1), ("x", "q", 2), ("y", "p", 4), ("y", "p", 8),
        ("x", "p", 16)]


@pytest.fixture(scope="module")
def gdf(spark):
    tbl = pa.table({
        "a": pa.array([r[0] for r in ROWS]),
        "b": pa.array([r[1] for r in ROWS]),
        "v": pa.array([r[2] for r in ROWS], pa.int64()),
    })
    df = spark.createDataFrame(tbl)
    df.createOrReplaceTempView("g")
    return df


def _key(t):
    return tuple((x is None, str(x)) for x in t)


def _norm(rows):
    return sorted((tuple(r.values()) for r in
                   (x.asDict() for x in rows)), key=_key)


@pytest.mark.slow
def test_rollup_sql(gdf, spark):
    got = _norm(spark.sql(
        "select a, b, sum(v) as s from g group by rollup(a, b)").collect())
    want = sorted([
        ("x", "p", 17), ("x", "q", 2), ("y", "p", 12),   # (a, b)
        ("x", None, 19), ("y", None, 12),                # (a)
        (None, None, 31),                                # ()
    ], key=_key)
    assert got == want


@pytest.mark.slow
def test_cube_sql(gdf, spark):
    got = _norm(spark.sql(
        "select a, b, sum(v) as s from g group by cube(a, b)").collect())
    # cube adds the (b)-only subtotals on top of rollup's sets
    assert (None, "p", 29) in got and (None, "q", 2) in got
    assert ("x", None, 19) in got and (None, None, 31) in got
    assert len(got) == 3 + 2 + 2 + 1


def test_grouping_sets_sql(gdf, spark):
    got = _norm(spark.sql(
        "select a, b, sum(v) as s from g "
        "group by grouping sets ((a, b), (b), ())").collect())
    assert ("x", "p", 17) in got
    assert (None, "p", 29) in got and (None, "q", 2) in got
    assert (None, None, 31) in got
    assert len(got) == 3 + 2 + 1


def test_grouping_and_grouping_id(gdf, spark):
    rows = spark.sql(
        "select a, grouping(a) as ga, grouping(b) as gb, "
        "grouping_id() as gid, sum(v) as s from g "
        "group by rollup(a, b)").collect()
    for r in rows:
        d = r.asDict()
        assert d["gid"] == d["ga"] * 2 + d["gb"]
        if d["a"] is None:
            assert d["ga"] == 1


def test_having_over_rollup(gdf, spark):
    got = _norm(spark.sql(
        "select a, b, sum(v) as s from g group by rollup(a, b) "
        "having sum(v) > 15").collect())
    assert got == sorted([("x", "p", 17), ("x", None, 19),
                          (None, None, 31)], key=_key)


def test_dataframe_rollup_cube(gdf):
    r = gdf.rollup("a").agg(F.sum("v").alias("s")).collect()
    got = {(x["a"], x["s"]) for x in r}
    assert got == {(None, 31), ("x", 19), ("y", 12)}
    c = gdf.cube("a", "b").agg(F.count("v").alias("c")).collect()
    assert len(c) == 3 + 2 + 2 + 1


def test_subtotal_null_vs_real_null(spark):
    """A REAL null key value must stay distinct from subtotal nulls
    (the grouping id disambiguates — reference Expand semantics)."""
    tbl = pa.table({
        "a": pa.array(["x", None, "x"]),
        "v": pa.array([1, 2, 4], pa.int64()),
    })
    spark.createDataFrame(tbl).createOrReplaceTempView("gn")
    rows = spark.sql(
        "select a, grouping(a) as ga, sum(v) as s from gn "
        "group by rollup(a)").collect()
    got = {(r["a"], r["ga"], r["s"]) for r in rows}
    # real-null group (ga=0) and the grand total (ga=1) both present
    assert ("x", 0, 5) in got
    assert (None, 0, 2) in got
    assert (None, 1, 7) in got


def test_having_key_and_grouping_refs(gdf, spark):
    got = _norm(spark.sql(
        "select a, b, sum(v) as s from g group by rollup(a, b) "
        "having a = 'x'").collect())
    assert got == sorted([("x", "p", 17), ("x", "q", 2), ("x", None, 19)],
                         key=_key)
    got2 = _norm(spark.sql(
        "select a, sum(v) as s from g group by rollup(a) "
        "having grouping(a) = 1").collect())
    assert got2 == [(None, 31)]


def test_grouping_sets_bare_key(gdf, spark):
    got = _norm(spark.sql(
        "select a, sum(v) as s from g "
        "group by grouping sets (a, ())").collect())
    assert got == sorted([("x", 19), ("y", 12), (None, 31)], key=_key)

"""Fast chaos-campaign smoke (spark_tpu/chaos.py) — three seeded
multi-point schedules through a live two-replica fleet, asserting the
full resilience contract on each: byte-identical-or-typed-error, zero
hangs, attempts within the unified retry budget, and the HBM
invariant. The 25-schedule campaign (kill-one-replica, A/B attempts)
lives in tools/chaos_campaign.py; this marker-gated smoke keeps the
contract under tier-1 without its runtime.
"""

import json
import urllib.request

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_tpu import chaos, faults, metrics
from spark_tpu.connect.server import Client
from spark_tpu.serve.router import serve_fleet

pytestmark = [pytest.mark.chaos, pytest.mark.timeout(240)]

_SMOKE_QUERIES = (
    "SELECT a, b FROM chaos_t WHERE a >= 8",
    "SELECT a % 4 AS g, SUM(b) AS s FROM chaos_t GROUP BY a % 4",
)


@pytest.fixture
def fleet(spark, tmp_path):
    path = str(tmp_path / "chaos_t.parquet")
    pq.write_table(pa.table({
        "a": list(range(64)),
        "b": [float(i) * 0.5 for i in range(64)]}), path)
    spark.read.parquet(path).createOrReplaceTempView("chaos_t")
    fl = serve_fleet(spark, replicas=2)
    try:
        yield fl
    finally:
        fl.stop()
        for k in list(spark.conf._overrides):
            if k.startswith("spark.tpu.faultInjection"):
                spark.conf.unset(k)
        faults.reset(spark.conf)
        rc = getattr(spark, "serve_result_cache", None)
        if rc is not None:
            rc.clear()
        metrics.reset_brownout()


def _workload(spark, url):
    rc = getattr(spark, "serve_result_cache", None)
    if rc is not None:
        rc.clear()  # faults must reach the engine, not a cached blob
    client = Client(url, timeout=20.0, retries=3)
    return b"\x00".join(
        json.dumps(client.sql(q).to_pydict(),
                   sort_keys=True).encode()
        for q in _SMOKE_QUERIES)


def test_chaos_smoke_three_schedules(spark, fleet):
    clean = _workload(spark, fleet.url)
    schedules = chaos.generate_campaign(7, 3)
    report = chaos.run_campaign(
        spark.conf, lambda: _workload(spark, fleet.url), schedules,
        clean_bytes=clean, alarm_s=60.0,
        queries=len(_SMOKE_QUERIES),
        memory_manager=spark.memory_manager)
    assert report.ok, [r.to_dict() for r in report.failures]
    assert len(report.results) == 3
    for r in report.results:
        assert r.outcome in ("identical", "typed_error")
        assert r.elapsed_s < 60.0  # zero hangs


def test_chaos_replay_artifact_roundtrip(tmp_path):
    sch = chaos.generate_campaign(3, 2)[1]
    art = tmp_path / "fail.json"
    art.write_text(json.dumps(
        {"schedule": sch.to_dict(), "ok": False,
         "outcome": "mismatch"}))
    assert chaos.replay_artifact(str(art)) == sch


def test_chaos_kill_and_revive_schedule(spark, fleet):
    """The campaign's kill-and-revive arc under tier-1: a replica dies
    (the DISPATCH finds the corpse inside the probe throttle and trips
    the breaker immediately), the fleet serves byte-identical results
    through the death, and the revived replica rejoins on its original
    port and serves again."""
    import time

    from spark_tpu.connect.server import ConnectServer

    clean = _workload(spark, fleet.url)
    fed = fleet.router.federation
    spark.conf.set("spark.tpu.serve.healthProbeSeconds", "3600.0")
    spark.conf.set("spark.tpu.serve.breaker.openSeconds", "0.3")
    try:
        fed.probe(force=True)
        for r in fed.replicas:
            r.breaker.reset()
            r.last_probe = time.time()  # probes throttled from here
        victim = fleet.replicas[0]
        host, port, rid = victim.host, victim.port, victim.replica_id
        victim.stop()
        during = _workload(spark, fleet.url)
        assert during == clean, "bytes changed during replica death"
        dead = next(r for r in fed.replicas if r.id == rid)
        assert dead.breaker.state == "open"  # one dispatch tripped it
        revived = ConnectServer(spark, host=host, port=port,
                                replica_id=rid).start()
        try:
            time.sleep(0.35)            # past breaker.openSeconds
            fed.probe(force=True)
            after = _workload(spark, fleet.url)
            assert after == clean, "bytes changed after revive"
            assert dead.healthy
        finally:
            revived.stop()
    finally:
        spark.conf.unset("spark.tpu.serve.healthProbeSeconds")
        spark.conf.unset("spark.tpu.serve.breaker.openSeconds")


def test_router_health_reports_resilience(spark, fleet):
    with urllib.request.urlopen(fleet.url + "/health",
                                timeout=10.0) as resp:
        h = json.loads(resp.read())
    assert "brownout" in h and "level" in h["brownout"]
    assert "retry_budget" in h and "draws" in h["retry_budget"]
    for rep in h["replicas"]:
        assert rep["breaker"]["state"] in ("closed", "open",
                                           "half_open")

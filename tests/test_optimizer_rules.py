"""Boolean simplification + condition-key extraction + nested-loop joins
(reference: optimizer/expressions.scala BooleanSimplification,
planning/patterns.scala ExtractEquiJoinKeys,
joins/BroadcastNestedLoopJoinExec.scala)."""

import pytest

from spark_tpu.expr import expressions as E
from spark_tpu.plan.optimizer import factor_or_common, split_conjuncts


def _c(n):
    return E.Col(n)


def test_factor_or_common_basic():
    a = E.Cmp("==", _c("x"), E.Literal(1))
    p = E.Cmp(">", _c("y"), E.Literal(2))
    q = E.Cmp("<", _c("y"), E.Literal(0))
    e = E.Or(E.And(a, p), E.And(a, q))
    out = factor_or_common(e)
    parts = split_conjuncts(out)
    keys = [E.expr_key(x) for x in parts]
    assert E.expr_key(a) in keys
    assert len(parts) == 2  # a AND (p OR q)


def test_factor_or_common_three_branches():
    a = E.Cmp("==", _c("x"), E.Literal(1))
    b = E.Cmp("==", _c("z"), E.Literal(9))
    p, q, r = (E.Cmp(">", _c("y"), E.Literal(i)) for i in (1, 2, 3))
    e = E.Or(E.Or(E.And(E.And(a, b), p), E.And(E.And(b, a), q)),
             E.And(E.And(a, r), b))
    out = factor_or_common(e)
    parts = split_conjuncts(out)
    keys = {E.expr_key(x) for x in parts}
    assert E.expr_key(a) in keys and E.expr_key(b) in keys


def test_factor_or_common_branch_fully_common():
    # (a AND p) OR a  ->  a  (the second branch reduces to TRUE)
    a = E.Cmp("==", _c("x"), E.Literal(1))
    p = E.Cmp(">", _c("y"), E.Literal(2))
    out = factor_or_common(E.Or(E.And(a, p), a))
    assert E.expr_key(out) == E.expr_key(a)


def test_factor_or_no_common():
    p = E.Cmp(">", _c("y"), E.Literal(2))
    q = E.Cmp("<", _c("y"), E.Literal(0))
    e = E.Or(p, q)
    assert factor_or_common(e) is e


def test_or_branch_join_key_extracted(spark):
    """q19 shape: equi key repeated in every OR branch must become a real
    equi join, not an all-pairs nested loop."""
    from spark_tpu.plan import logical as L
    from spark_tpu.plan.optimizer import optimize
    from spark_tpu.sql.parser import parse_sql

    a = spark.createDataFrame(
        [{"k": i, "u": i % 5} for i in range(40)])
    b = spark.createDataFrame(
        [{"j": i, "w": i % 7} for i in range(40)])
    a.createOrReplaceTempView("ta")
    b.createOrReplaceTempView("tb")
    sql = ("select count(*) as n from ta, tb where "
           "(k = j and u > 1 and w < 5) or (k = j and u <= 1 and w >= 5)")
    plan = optimize(parse_sql(sql, spark.catalog))
    joins = []

    def walk(node):
        if isinstance(node, L.Join):
            joins.append(node)
        for ch in node.children():
            walk(ch)

    walk(plan)
    assert joins and joins[0].left_keys, "equi key was not extracted"
    got = spark.sql(sql).collect()[0].n
    want = sum(1 for i in range(40)
               if (i % 5 > 1 and i % 7 < 5) or (i % 5 <= 1 and i % 7 >= 5))
    assert got == want


def test_condition_only_inner_join(spark):
    """Inequality-band join runs through the chunked nested loop."""
    a = spark.createDataFrame([{"x": i} for i in range(50)])
    b = spark.createDataFrame([{"y": i * 10} for i in range(10)])
    a.createOrReplaceTempView("nla")
    b.createOrReplaceTempView("nlb")
    rows = spark.sql(
        "select x, y from nla, nlb where y > x * 9 and y <= x * 9 + 10"
    ).collect()
    want = {(x, y) for x in range(50) for y in range(0, 100, 10)
            if y > x * 9 and y <= x * 9 + 10}
    assert {(r.x, r.y) for r in rows} == want


@pytest.mark.parametrize("how", ["left", "right", "full", "semi", "anti"])
def test_condition_only_outer_semi_joins(spark, how):
    from spark_tpu.api import functions as F

    a = spark.createDataFrame([{"x": 1}, {"x": 5}, {"x": 9}])
    b = spark.createDataFrame([{"y": 4}, {"y": 6}])
    cond = F.col("x") > F.col("y")
    mapped = {"semi": "left_semi", "anti": "left_anti"}.get(how, how)
    rows = a.join(b, on=cond, how=mapped).collect()
    matches = {(x, y) for x in (1, 5, 9) for y in (4, 6) if x > y}
    if mapped == "left_semi":
        assert sorted(r.x for r in rows) == [5, 9]
    elif mapped == "left_anti":
        assert sorted(r.x for r in rows) == [1]
    else:
        got = {(r.x, r.y) for r in rows}
        assert matches <= got
        if mapped in ("left", "full"):
            assert (1, None) in got
        if mapped in ("right", "full"):
            # every right row matched something here; sanity only
            assert all(y in (4, 6, None) for _, y in got)


def test_semi_join_condition_key_extracted(spark):
    """EXISTS-derived semi joins whose equality lives in the condition
    must get equi keys (extract_condition_keys uses the PAIR namespace,
    not the left-only semi-join schema)."""
    from spark_tpu.plan import logical as L
    from spark_tpu.plan.optimizer import extract_condition_keys
    from spark_tpu.expr import expressions as E

    a = spark.createDataFrame([{"a": 1}, {"a": 2}])
    b = spark.createDataFrame([{"c": 2}, {"c": 3}])
    join = L.Join(a._plan, b._plan, "left_semi", (), (),
                  E.Cmp("==", E.Col("a"), E.Col("c")))
    out = extract_condition_keys(join)
    assert out.left_keys and out.condition is None


def test_runtime_filter_semi_join_reduction(spark, tmp_path):
    """Inner join with a filtered small side and a big scan side gets a
    semi-join reduction injected on the big side (reference:
    InjectRuntimeFilter.scala:36), without changing results."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_tpu import metrics
    from spark_tpu.plan import logical as L
    from spark_tpu.plan.optimizer import optimize

    n = 1 << 15
    spark.conf.set("spark.tpu.runtimeFilter.minRows", n)
    rng = np.random.default_rng(5)
    pq.write_table(pa.table({
        "k": pa.array(rng.integers(0, 1000, n), pa.int64()),
        "v": pa.array(rng.random(n)),
    }), str(tmp_path / "big.parquet"))
    big = spark.read.parquet(str(tmp_path / "big.parquet"))
    small = spark.createDataFrame(pa.table({
        "k": pa.array(np.arange(1000), pa.int64()),
        "grp": pa.array((np.arange(1000) % 7).astype("int64")),
    })).filter("grp = 3")
    big.createOrReplaceTempView("rf_big")
    small.createOrReplaceTempView("rf_small")

    df = spark.sql("select count(*) as c, sum(v) as s from rf_big "
                   "join rf_small on rf_big.k = rf_small.k")
    want = df.collect()[0]  # default: rule off
    spark.conf.set("spark.tpu.runtimeFilter.semiJoinReduction", True)
    try:
        lp = optimize(df._plan)
        semis = [j for j in L.collect_nodes(lp, L.Join)
                 if j.how == "left_semi"]
        assert semis, "no semi-join reduction injected"
        got = df.collect()[0]
    finally:
        spark.conf.unset("spark.tpu.runtimeFilter.semiJoinReduction")
        spark.conf.unset("spark.tpu.runtimeFilter.minRows")
    assert got["c"] == want["c"]
    assert abs(got["s"] - want["s"]) < 1e-9 * max(1.0, abs(want["s"]))

"""Fleet-grade resilience primitives: end-to-end deadline propagation
(spark_tpu/deadline.py), the unified per-query retry budget
(recovery.RetryBudget), the per-replica circuit breaker + fleet
brownout (serve/federation.py), per-point fault RNG isolation, and the
retry-budget lint rule.

Every test carries the ``timeout`` deadlock guard — a deadline that
fails to fire must fail the test, never hang tier-1.
"""

import ast
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from spark_tpu import chaos, deadline, faults, metrics, recovery, tracing
from spark_tpu.conf import RuntimeConf
from spark_tpu.serve.federation import BrownoutController, CircuitBreaker

pytestmark = pytest.mark.timeout(90)


# ---- deadline propagation ---------------------------------------------------


def test_deadline_mint_bind_remaining():
    assert deadline.current() is None
    assert deadline.remaining() is None
    assert not deadline.expired()
    dl = deadline.mint(5.0)
    with deadline.bind(dl):
        assert deadline.current() == dl
        rem = deadline.remaining()
        assert 0.0 < rem <= 5.0
        assert not deadline.expired()
        deadline.check("test")  # no raise
    assert deadline.current() is None


def test_deadline_mint_none_for_nonpositive():
    assert deadline.mint(None) is None
    assert deadline.mint(0.0) is None
    assert deadline.mint(-3.0) is None


def test_deadline_tighter_ambient_wins():
    outer = deadline.mint(100.0)
    inner = deadline.mint(1.0)
    with deadline.bind(outer):
        with deadline.bind(inner):
            assert deadline.current() == min(outer, inner) == inner
        # a LOOSER inner bind cannot extend the outer window
        with deadline.bind(deadline.mint(500.0)):
            assert deadline.current() == outer
        assert deadline.current() == outer


def test_deadline_check_raises_typed():
    with deadline.bind(time.time() - 0.01):
        assert deadline.expired()
        with pytest.raises(deadline.DeadlineExceeded,
                           match="DEADLINE_EXCEEDED at somewhere"):
            deadline.check("somewhere")


def test_deadline_cap_sleep():
    assert deadline.cap_sleep(3.0) == 3.0  # unbound: unchanged
    with deadline.bind(time.time() + 0.2):
        assert deadline.cap_sleep(10.0) <= 0.2
        assert deadline.cap_sleep(0.05) == pytest.approx(0.05, abs=0.01)
    with deadline.bind(time.time() - 1.0):
        assert deadline.cap_sleep(10.0) == 0.0


def test_deadline_header_roundtrip():
    dl = time.time() + 12.5
    with deadline.bind(dl):
        hv = deadline.header_value()
    assert hv is not None
    back = deadline.from_header(hv)
    assert back == pytest.approx(dl, abs=1e-3)
    assert deadline.from_header(None) is None
    assert deadline.from_header("garbage") is None


def test_deadline_exceeded_not_transient():
    """The typed deadline error must NOT be re-retried by outer layers
    even though its message carries the DEADLINE_EXCEEDED marker."""
    e = deadline.DeadlineExceeded("layer", time.time() - 1.0)
    assert "DEADLINE_EXCEEDED" in str(e)
    assert not recovery.is_transient(e)
    # ... even when wrapped as a cause of a generic error
    wrapper = RuntimeError("stage failed")
    wrapper.__cause__ = e
    assert not recovery.is_transient(wrapper)


# ---- unified retry budget ---------------------------------------------------


def test_retry_budget_pool_shared_across_layers():
    b = recovery.RetryBudget(4, layer_floor=0)
    granted = sum(b.draw("a") for _ in range(3))
    granted += sum(b.draw("b") for _ in range(3))
    assert granted == 4  # ONE pool, not 3 per layer
    assert b.draw("c") is False
    snap = b.snapshot()
    assert snap["remaining"] == 0
    assert snap["draws"] == 4
    assert set(snap["layers"]) == {"a", "b"}


def test_retry_budget_layer_floor():
    """An exhausted pool still grants each layer its floor so one noisy
    layer cannot starve every other layer's FIRST retry."""
    b = recovery.RetryBudget(2, layer_floor=1)
    assert b.draw("noisy") and b.draw("noisy")
    assert not b.draw("noisy")  # pool gone, floor already used
    assert b.draw("quiet")      # floor guarantee for a fresh layer
    assert not b.draw("quiet")


def test_retry_budget_exhausted_typed_and_not_transient():
    b = recovery.RetryBudget(1)
    b.draw("x")
    err = recovery.RetryBudgetExhausted("x", b)
    assert "RETRY_BUDGET_EXHAUSTED" in str(err)
    assert not recovery.is_transient(err)


def test_retry_budget_metrics_events():
    metrics.reset_retry_budget()
    b = recovery.RetryBudget(2, layer_floor=0)
    b.draw("layer1")
    b.draw("layer1")
    b.draw("layer1")  # denied
    st = metrics.retry_budget_stats()
    assert st["draws"] == 2
    assert st["denials"] == 1
    evs = [e for e in metrics.recent(64)
           if e["kind"] == "retry_draw" and e["layer"] == "layer1"]
    assert len(evs) == 3  # every draw (granted or denied) is an event
    assert [e["granted"] for e in evs] == [True, True, False]


def test_retry_allowed_legacy_counter_without_budget():
    """No ambient budget -> the seam allows the retry but counts it as
    a legacy attempt (the A/B counter for the campaign)."""
    metrics.reset_retry_budget()
    assert recovery.current_budget() is None
    assert recovery.retry_allowed("anything") is True
    assert metrics.retry_budget_stats()["legacy_attempts"] == 1


def test_budget_from_conf_and_binding():
    conf = RuntimeConf({"spark.tpu.recovery.retryBudget.attempts": 3})
    b = recovery.budget_from_conf(conf)
    assert b is not None and b.snapshot()["attempts"] == 3
    with recovery.bind_budget(b):
        assert recovery.current_budget() is b
        assert recovery.retry_allowed("seam") is True
    assert recovery.current_budget() is None
    off = RuntimeConf(
        {"spark.tpu.recovery.retryBudget.enabled": False})
    assert recovery.budget_from_conf(off) is None


def test_backoff_sleep_capped_by_deadline():
    b = recovery.RetryBudget(4, backoff_base_s=50.0, backoff_cap_s=50.0)
    with deadline.bind(time.time() + 0.15):
        t0 = time.perf_counter()
        b.sleep(3)  # uncapped this would be tens of seconds
        assert time.perf_counter() - t0 < 1.0


# ---- client fail-fast (satellite 1) -----------------------------------------


class _Always429(BaseHTTPRequestHandler):
    """A server whose Retry-After hint (10s) far exceeds any sane
    client timeout — the old client slept through its own deadline."""

    def log_message(self, *a):
        pass

    def do_POST(self):
        n = int(self.headers.get("Content-Length", "0"))
        self.rfile.read(n)
        body = json.dumps({"error": "SchedulerQueueFull",
                           "message": "full", "retry_after_s": 10.0}
                          ).encode()
        self.send_response(429)
        self.send_header("Content-Type", "application/json")
        self.send_header("Retry-After", "10")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def test_client_never_sleeps_past_its_deadline():
    from spark_tpu.connect.server import Client

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Always429)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        client = Client(url, timeout=0.8, retries=8)
        t0 = time.perf_counter()
        with pytest.raises(deadline.DeadlineExceeded):
            client.sql("SELECT 1")
        elapsed = time.perf_counter() - t0
        # one 10s Retry-After floor would already blow this bound
        assert elapsed < 5.0
    finally:
        httpd.shutdown()
        httpd.server_close()
        thread.join(timeout=5.0)


# ---- circuit breaker --------------------------------------------------------


def _breaker(**over):
    base = {"spark.tpu.serve.breaker.minRequests": 2,
            "spark.tpu.serve.breaker.openSeconds": 0.05,
            "spark.tpu.serve.breaker.failureRate": 0.5}
    base.update(over)
    return CircuitBreaker(RuntimeConf(base))


def test_breaker_opens_on_failure_rate():
    br = _breaker()
    assert br.admits()
    br.failure()
    assert br.state == "closed"  # below minRequests
    br.failure()
    assert br.state == "open"
    assert not br.admits()


def test_breaker_half_open_probe_then_close():
    br = _breaker()
    br.failure()
    br.failure()
    assert br.state == "open"
    time.sleep(0.06)
    assert br.admits()  # transitions to half_open
    assert br.state == "half_open"
    br.begin()
    assert not br.admits()  # single probe trickle
    br.success()
    assert br.state == "closed"
    assert br.admits()
    transitions = [(a, b) for _, a, b in br.state_changes]
    assert transitions == [("closed", "open"), ("open", "half_open"),
                           ("half_open", "closed")]


def test_breaker_half_open_failure_reopens():
    br = _breaker()
    br.failure()
    br.failure()
    time.sleep(0.06)
    assert br.admits()
    br.begin()
    br.failure()
    assert br.state == "open"
    assert not br.admits()


def test_breaker_successes_keep_rate_low():
    br = _breaker()
    for _ in range(8):
        br.success()
    br.failure()
    br.failure()
    # 2 failures / 10 outcomes = 0.2 < 0.5 threshold
    assert br.state == "closed"


def test_breaker_disabled_is_transparent():
    br = CircuitBreaker(RuntimeConf(
        {"spark.tpu.serve.breaker.enabled": False}))
    for _ in range(10):
        br.failure()
    assert br.state == "closed" and br.admits()


# ---- brownout ---------------------------------------------------------------


def test_brownout_enters_and_exits_with_hysteresis():
    metrics.reset_brownout()
    bo = BrownoutController(RuntimeConf({
        "spark.tpu.serve.brownout.minEvents": 4,
        "spark.tpu.serve.brownout.enterRate": 0.5,
        "spark.tpu.serve.brownout.exitRate": 0.1}))
    try:
        for _ in range(4):
            bo.note("failure")
        assert bo.level == 1
        assert metrics.brownout_level() == 1
        # pressure between exit and enter rate: level HOLDS
        for _ in range(4):
            bo.note("ok")
        assert bo.level == 1
        for _ in range(32):
            bo.note("ok")
        assert bo.level == 0
        assert metrics.brownout_level() == 0
        st = metrics.brownout_stats()
        assert st["entered"] == 1 and st["exited"] == 1
    finally:
        metrics.reset_brownout()


def test_brownout_sheds_trace_sampling_and_prewarm():
    from spark_tpu import trace as trace_mod

    metrics.reset_brownout()
    try:
        metrics.set_brownout(1)
        assert trace_mod._sample_root() is False
    finally:
        metrics.reset_brownout()


def test_serve_profile_reports_resilience():
    p = tracing.serve_profile(events=[])
    assert "resilience" in p
    assert set(p["resilience"]) == {"brownout", "retry_budget"}


# ---- per-point fault RNG isolation (satellite 2) ----------------------------


def _fire_pattern(conf, point, n):
    pat = []
    for _ in range(n):
        try:
            faults.inject(point, conf)
            pat.append(False)
        except faults.InjectedFault:
            pat.append(True)
    return pat


def test_prob_fault_streams_isolated_per_point():
    """One point's arrival count must never perturb another's draw
    sequence: the pattern for point A is identical whether or not
    point B is armed and firing between A's arrivals."""
    spec = "prob:0.5:424242"
    key_a = "spark.tpu.faultInjection.execute.device"
    key_b = "spark.tpu.faultInjection.scheduler.admit"
    alone = _fire_pattern(
        RuntimeConf({key_a: spec}), "execute.device", 40)
    conf = RuntimeConf({key_a: spec, key_b: spec})
    mixed = []
    for i in range(40):
        try:
            faults.inject("execute.device", conf)
            mixed.append(False)
        except faults.InjectedFault:
            mixed.append(True)
        try:
            faults.inject("scheduler.admit", conf)
        except faults.InjectedFault:
            pass
    assert mixed == alone
    assert 0 < sum(alone) < 40  # the stream actually fires sometimes


def test_prob_fault_streams_differ_between_points():
    """Same campaign seed, different points -> DECORRELATED streams
    (the old shared-seed bug made every point fire in lockstep)."""
    spec = "prob:0.5:777"
    pat_a = _fire_pattern(RuntimeConf(
        {"spark.tpu.faultInjection.execute.device": spec}),
        "execute.device", 64)
    pat_b = _fire_pattern(RuntimeConf(
        {"spark.tpu.faultInjection.scheduler.admit": spec}),
        "scheduler.admit", 64)
    assert pat_a != pat_b


# ---- deadline expiry while QUEUED (satellite 3) -----------------------------


class _MiniSession:
    """Duck-typed session: conf + unified memory manager, nothing else
    (the scheduler only reads those two)."""

    def __init__(self, conf, mm):
        self.conf = conf
        self.memory_manager = mm


def test_deadline_expired_in_queue_zero_executions_zero_grants():
    from spark_tpu.scheduler import QueryCancelled, QueryScheduler
    from spark_tpu.storage.unified import UnifiedMemoryManager

    conf = RuntimeConf({"spark.tpu.scheduler.maxConcurrency": 1})
    mm = UnifiedMemoryManager(budget_bytes=1 << 24, conf=conf)
    sched = QueryScheduler(_MiniSession(conf, mm))
    release = threading.Event()
    ran = threading.Event()
    try:
        blocker = sched.submit(lambda tk: release.wait(30))
        t0 = time.time() + 30
        while blocker.state != "RUNNING" and time.time() < t0:
            time.sleep(0.005)
        grants_before = mm.snapshot()["grants"]["grants"]

        def work(tk):
            ran.set()
            return "late"

        t = sched.submit(work, deadline_s=0.05)
        time.sleep(0.1)  # deadline passes while QUEUED behind blocker
        release.set()
        with pytest.raises(QueryCancelled, match="DEADLINE_EXCEEDED"):
            t.result(timeout=30)
        blocker.result(timeout=30)
        assert not ran.is_set()  # ZERO device executions
        snap = mm.snapshot()
        assert snap["grants"]["grants"] == grants_before  # ZERO grants
        assert snap["in_use_bytes"] == 0
        assert (snap["in_use_bytes"] + snap["storage_bytes"]
                <= snap["budget_bytes"])
    finally:
        release.set()
        sched.stop()


# ---- scheduler merges the propagated deadline -------------------------------


def test_scheduler_submit_merges_ambient_deadline():
    from spark_tpu.scheduler import QueryScheduler

    sched = QueryScheduler(conf=RuntimeConf())
    try:
        tight = time.time() + 0.5
        with deadline.bind(tight):
            t = sched.submit(lambda tk: "ok", deadline_s=600.0)
        assert t.deadline == pytest.approx(tight, abs=1e-6)
        assert t.result(timeout=30) == "ok"
    finally:
        sched.stop()


# ---- lint rule 7: retry loops draw from the budget --------------------------


_VIOLATION = '''
def retry_without_budget(fn, retries):
    for attempt in range(retries + 1):
        try:
            return fn()
        except Exception:
            continue
'''

_CLEAN = '''
def retry_with_budget(fn, retries):
    from spark_tpu import recovery
    for attempt in range(retries + 1):
        try:
            return fn()
        except Exception:
            if not recovery.retry_allowed("seam"):
                raise
'''

_NOT_A_RETRY = '''
def plain_loop(items):
    for i in range(len(items)):
        items[i] += 1
'''


def test_lint_rule7_flags_unbudgeted_retry_loop():
    from tools.lint_invariants import DEFAULT_CONFIG, _check_retry_budget

    out = []
    _check_retry_budget(ast.parse(_VIOLATION), "x.py",
                        dict(DEFAULT_CONFIG), out)
    assert len(out) == 1 and out[0].rule == "retry-budget"
    out = []
    _check_retry_budget(ast.parse(_CLEAN), "x.py",
                        dict(DEFAULT_CONFIG), out)
    assert out == []
    out = []
    _check_retry_budget(ast.parse(_NOT_A_RETRY), "x.py",
                        dict(DEFAULT_CONFIG), out)
    assert out == []


def test_lint_rule7_exemption():
    from tools.lint_invariants import DEFAULT_CONFIG, _check_retry_budget

    cfg = dict(DEFAULT_CONFIG)
    cfg["retry_loop_allow"] = ["x.py:retry_without_budget"]
    out = []
    _check_retry_budget(ast.parse(_VIOLATION), "x.py", cfg, out)
    assert out == []


def test_lint_clean_tree():
    """The converted tree passes rule 7 (and every other rule)."""
    from tools.lint_invariants import run_lint

    assert [f.format() for f in run_lint()] == []


# ---- chaos harness units ----------------------------------------------------


def test_campaign_generation_deterministic():
    a = chaos.generate_campaign(99, 10)
    b = chaos.generate_campaign(99, 10)
    assert [s.to_dict() for s in a] == [s.to_dict() for s in b]
    c = chaos.generate_campaign(100, 10)
    assert [s.to_dict() for s in a] != [s.to_dict() for s in c]
    for s in a:
        assert 1 <= len(s.faults) <= 3
        for f in s.faults:
            assert f.point in faults.POINTS
            assert f.kind in faults.KINDS
            faults.parse_spec(f.spec())  # grammar round-trip


def test_chaos_schedule_json_roundtrip():
    sch = chaos.generate_campaign(5, 3)[2]
    back = chaos.ChaosSchedule.from_dict(
        json.loads(json.dumps(sch.to_dict())))
    assert back == sch


def test_is_typed_error_classification():
    assert chaos.is_typed_error(
        faults.InjectedTransientError("p", "UNAVAILABLE: x"))
    assert chaos.is_typed_error(
        deadline.DeadlineExceeded("w", time.time()))
    assert chaos.is_typed_error(
        recovery.RetryBudgetExhausted("l", None))
    assert chaos.is_typed_error(RuntimeError("DEADLINE_EXCEEDED: t/o"))
    wrapped = RuntimeError("stage failed")
    wrapped.__cause__ = faults.InjectedCorruptionError("p", "DATA_LOSS")
    assert chaos.is_typed_error(wrapped)
    assert not chaos.is_typed_error(AttributeError("oops"))
    assert not chaos.is_typed_error(RuntimeError("segfault adjacent"))

"""Arrays, explode/posexplode, LATERAL VIEW, struct field access
(reference: generators.scala / GenerateExec.scala:1,
collectionOperations.scala, complexTypeCreator.scala, UnsafeArrayData).
Device layout: padded 2D values + hidden '#len' companion column
(types.ArrayType)."""

import pyarrow as pa
import pytest

from spark_tpu.api import functions as F


@pytest.fixture(scope="module")
def arr_df(spark):
    tbl = pa.table({
        "id": pa.array([1, 2, 3, 4], pa.int64()),
        "xs": pa.array([[10, 11], [20], [], [30, 31, 32]],
                       pa.list_(pa.int64())),
        "tags": pa.array([["a", "b"], ["c"], None, ["a"]],
                         pa.list_(pa.string())),
        "csv": pa.array(["x,y", "z", "p,q,r", ""]),
    })
    df = spark.createDataFrame(tbl)
    df.createOrReplaceTempView("arrs")
    return df


def test_roundtrip_and_size(arr_df):
    rows = arr_df.select(F.col("id"), F.col("xs"),
                         F.size("xs").alias("n")).collect()
    assert [r["xs"] for r in rows] == [[10, 11], [20], [], [30, 31, 32]]
    assert [r["n"] for r in rows] == [2, 1, 0, 3]


def test_string_array_roundtrip_and_null(arr_df):
    rows = arr_df.select("tags").collect()
    assert [r["tags"] for r in rows] == [["a", "b"], ["c"], None, ["a"]]


def test_element_at(arr_df):
    rows = arr_df.select(
        F.element_at("xs", 1).alias("first"),
        F.element_at("xs", -1).alias("last"),
        F.element_at("xs", 5).alias("oob")).collect()
    assert [r["first"] for r in rows] == [10, 20, None, 30]
    assert [r["last"] for r in rows] == [11, 20, None, 32]
    assert [r["oob"] for r in rows] == [None, None, None, None]


def test_array_contains(arr_df):
    rows = arr_df.select(
        F.array_contains("xs", 20).alias("i"),
        F.array_contains("tags", "a").alias("s")).collect()
    assert [r["i"] for r in rows] == [False, True, False, False]
    assert [r["s"] for r in rows] == [True, False, None, True]


def test_make_array_and_split(arr_df, spark):
    rows = arr_df.select(
        F.array(F.col("id"), F.lit(0)).alias("pair"),
        F.split("csv", ",").alias("parts")).collect()
    assert [r["pair"] for r in rows] == [[1, 0], [2, 0], [3, 0], [4, 0]]
    assert [r["parts"] for r in rows] == [
        ["x", "y"], ["z"], ["p", "q", "r"], [""]]


def test_explode_select(arr_df):
    rows = arr_df.select(F.col("id"),
                         F.explode("xs").alias("x")).collect()
    got = [(r["id"], r["x"]) for r in rows]
    # empty arrays produce no rows (reference explode semantics)
    assert got == [(1, 10), (1, 11), (2, 20), (4, 30), (4, 31), (4, 32)]


def test_explode_reexecution_traced(arr_df):
    df = arr_df.select(F.col("id"), F.explode("xs").alias("x"))
    first = [(r["id"], r["x"]) for r in df.collect()]
    second = [(r["id"], r["x"]) for r in df.collect()]  # adaptive replay
    assert first == second


def test_posexplode(arr_df, spark):
    rows = spark.sql(
        "select id, pos, x from arrs "
        "lateral view posexplode(xs) v as pos, x").collect()
    got = [(r["id"], r["pos"], r["x"]) for r in rows]
    assert got == [(1, 0, 10), (1, 1, 11), (2, 0, 20),
                   (4, 0, 30), (4, 1, 31), (4, 2, 32)]


def test_lateral_view_sql(arr_df, spark):
    rows = spark.sql(
        "select id, t from arrs lateral view explode(tags) v as t "
        "where t = 'a'").collect()
    assert [(r["id"], r["t"]) for r in rows] == [(1, "a"), (4, "a")]


def test_explode_then_aggregate(arr_df, spark):
    rows = spark.sql(
        "select t, count(*) as c from arrs "
        "lateral view explode(tags) v as t group by t "
        "order by t").collect()
    assert [(r["t"], r["c"]) for r in rows] == [
        ("a", 2), ("b", 1), ("c", 1)]


def test_split_explode_wordcount(spark):
    tbl = pa.table({"line": pa.array(["a b a", "b c", "a"])})
    spark.createDataFrame(tbl).createOrReplaceTempView("lines")
    rows = spark.sql(
        "select w, count(*) as c from lines "
        "lateral view explode(split(line, ' ')) v as w "
        "group by w order by c desc, w").collect()
    assert [(r["w"], r["c"]) for r in rows] == [
        ("a", 3), ("b", 2), ("c", 1)]


def test_struct_flatten_field_access(spark):
    tbl = pa.table({
        "s": pa.array([{"x": 1, "y": "u"}, {"x": 2, "y": "v"}],
                      pa.struct([("x", pa.int64()), ("y", pa.string())])),
        "k": pa.array([10, 20], pa.int64()),
    })
    df = spark.createDataFrame(tbl)
    # structs flatten at ingest into dotted columns
    rows = df.select(F.col("s.x"), F.col("k")).collect()
    assert [r["s.x"] for r in rows] == [1, 2]
    df.createOrReplaceTempView("st")
    got = spark.sql('select `s.y` as y from st where `s.x` = 2').collect()
    assert [r["y"] for r in got] == ["v"]


def test_arrays_through_joins(spark):
    """Array columns survive joins (the padded-2D + companion layout
    rides every gather path as ordinary columns)."""
    left = spark.createDataFrame(pa.table({
        "k": pa.array([1, 2], pa.int64()),
        "xs": pa.array([[7, 8], [9]], pa.list_(pa.int64())),
    }))
    right = spark.createDataFrame(pa.table({
        "k": pa.array([1, 2], pa.int64()),
        "v": pa.array(["l", "r"]),
    }))
    rows = left.join(right, on="k").select("k", "xs", "v") \
        .orderBy("k").collect()
    assert [r["xs"] for r in rows] == [[7, 8], [9]]


def test_list_ingest_null_row_with_value_range(spark):
    """A null list slot may still own a value range (legal Arrow built
    via from_arrays + mask); later rows must not misalign."""
    import numpy as np

    offsets = pa.array([0, 2, 5, 7], pa.int32())
    values = pa.array([1, 2, 3, 4, 5, 6, 7], pa.int64())
    arr = pa.ListArray.from_arrays(
        offsets, values, mask=pa.array([False, True, False]))
    df = spark.createDataFrame(pa.table({"xs": arr}))
    rows = df.select("xs").collect()
    assert rows[0]["xs"] == [1, 2]
    assert rows[1]["xs"] is None
    assert rows[2]["xs"] == [6, 7]


def test_list_ingest_all_empty(spark):
    df = spark.createDataFrame(pa.table({
        "xs": pa.array([[], []], pa.list_(pa.int64()))}))
    rows = df.select(F.size("xs").alias("n")).collect()
    assert [r["n"] for r in rows] == [0, 0]


def test_struct_null_rows_propagate(spark):
    tbl = pa.table({"s": pa.array(
        [{"a": 1, "b": 2.0}, None, {"a": 3, "b": 4.0}],
        pa.struct([("a", pa.int64()), ("b", pa.float64())]))})
    df = spark.createDataFrame(tbl)
    rows = df.select(F.col("s.a"), F.col("s.b")).collect()
    assert [r["s.a"] for r in rows] == [1, None, 3]
    assert [r["s.b"] for r in rows] == [2.0, None, 4.0]


def test_make_array_nullable_input_nulls_whole_row(spark):
    # null ELEMENTS are not representable in the padded layout: a null
    # input nulls the WHOLE array row (documented ArrayType deviation)
    tbl = pa.table({"x": pa.array([1, None], pa.int64())})
    df = spark.createDataFrame(tbl)
    rows = df.select(F.array(F.col("x"), F.lit(1)).alias("a")).collect()
    assert rows[0]["a"] == [1, 1]
    assert rows[1]["a"] is None


def test_array_contains_float_needle_no_truncate(arr_df):
    rows = arr_df.select(
        F.array_contains("xs", F.lit(10.5)).alias("c")).collect()
    assert [r["c"] for r in rows] == [False, False, False, False]


def test_lateral_view_without_view_alias(arr_df, spark):
    rows = spark.sql(
        "select id, t from arrs lateral view explode(tags) as t "
        "where t = 'c'").collect()
    assert [(r["id"], r["t"]) for r in rows] == [(2, "c")]

"""Connect server: SQL over HTTP with Arrow IPC results (reference:
connector/connect SparkConnectService + thriftserver)."""

import pytest

from spark_tpu.connect import Client, ConnectServer


@pytest.fixture()
def server(spark):
    spark.createDataFrame(
        [{"k": i % 3, "v": i} for i in range(30)]
    ).createOrReplaceTempView("conn_t")
    srv = ConnectServer(spark, port=0).start()
    yield srv
    srv.stop()


def test_sql_roundtrip(server):
    c = Client(server.url)
    tbl = c.sql("select k, sum(v) as s from conn_t group by k order by k")
    rows = tbl.to_pylist()
    assert rows == [
        {"k": 0, "s": sum(range(0, 30, 3))},
        {"k": 1, "s": sum(range(1, 30, 3))},
        {"k": 2, "s": sum(range(2, 30, 3))}]


def test_tables_and_errors(server):
    c = Client(server.url)
    assert "conn_t" in c.tables()
    with pytest.raises(RuntimeError):
        c.sql("select * from does_not_exist")


def test_typed_plan_protocol(spark):
    """Decoupled client builds a typed JSON logical plan (no engine
    imports) and the server decodes/executes it (reference:
    relations.proto + SparkConnectPlanner.scala:67)."""
    from spark_tpu.connect.server import (Client, ConnectServer, col,
                                          fn, lit)

    spark.createDataFrame(
        [{"k": i % 3, "v": i, "s": "ab"[i % 2]} for i in range(30)]
    ).createOrReplaceTempView("cp_t")
    spark.createDataFrame(
        [{"k": i, "w": i * 10} for i in range(3)]
    ).createOrReplaceTempView("cp_d")

    srv = ConnectServer(spark, port=0).start()
    try:
        c = Client(srv.url)
        out = (c.table("cp_t")
               .filter({"e": "bin", "op": ">", "left": col("v"),
                        "right": lit(4)})
               .groupBy("k")
               .agg(n=fn("count", "v"),
                    sv=fn("sum", "v"),
                    ds=fn("count", "s", distinct=True))
               .sort("k")
               .toArrow())
        rows = out.to_pylist()
        assert [r["k"] for r in rows] == [0, 1, 2]
        assert sum(r["n"] for r in rows) == 25
        assert all(r["ds"] <= 2 for r in rows)

        # join through the protocol (USING semantics: k appears once)
        j = (c.table("cp_t").join(c.table("cp_d"), on="k")
             .select("k", "v", "w").sort("v").limit(5).toArrow())
        assert j.column_names == ["k", "v", "w"]
        assert j.num_rows == 5
        assert j.to_pylist()[0]["w"] == j.to_pylist()[0]["k"] * 10

        # unknown function -> structured error
        try:
            c.table("cp_t").select(fn("no_such_fn", "v")).toArrow()
            assert False, "expected error"
        except RuntimeError as e:
            assert "no_such_fn" in str(e)
    finally:
        srv.stop()


def test_using_right_join_keys_from_right(spark):
    """RIGHT USING join: unmatched right rows carry NULL in the left
    region, so the merged key column must be projected from the RIGHT
    side (still under the un-suffixed output name)."""
    from spark_tpu.connect.server import Client, ConnectServer

    spark.createDataFrame(
        [{"k": 1, "v": 10}, {"k": 2, "v": 20}]
    ).createOrReplaceTempView("cpr_l")
    spark.createDataFrame(
        [{"k": 2, "w": 200}, {"k": 3, "w": 300}]
    ).createOrReplaceTempView("cpr_r")
    srv = ConnectServer(spark, port=0).start()
    try:
        c = Client(srv.url)
        j = (c.table("cpr_l").join(c.table("cpr_r"), on="k", how="right")
             .sort("k").toArrow())
        assert j.column_names == ["k", "v", "w"]
        assert j.to_pylist() == [
            {"k": 2, "v": 20, "w": 200},
            {"k": 3, "v": None, "w": 300}]  # k=3, not NULL
    finally:
        srv.stop()


def test_using_full_join_coalesced_keys(spark):
    """FULL USING join: either region may hold the NULL key, so the
    merged key column is coalesce(left.k, right.k) — the key appears
    once and is never NULL for a row that exists on either side."""
    from spark_tpu.connect.server import Client, ConnectServer

    spark.createDataFrame(
        [{"k": 1, "v": 10}, {"k": 2, "v": 20}]
    ).createOrReplaceTempView("cpf_l")
    spark.createDataFrame(
        [{"k": 2, "w": 200}, {"k": 3, "w": 300}]
    ).createOrReplaceTempView("cpf_r")
    srv = ConnectServer(spark, port=0).start()
    try:
        c = Client(srv.url)
        j = (c.table("cpf_l").join(c.table("cpf_r"), on="k", how="full")
             .sort("k").toArrow())
        assert j.column_names == ["k", "v", "w"]
        assert j.to_pylist() == [
            {"k": 1, "v": 10, "w": None},
            {"k": 2, "v": 20, "w": 200},
            {"k": 3, "v": None, "w": 300}]  # k=3 from the right side
    finally:
        srv.stop()


def test_fn_dispatch_is_allowlisted():
    """Module attributes that happen to be callable are not protocol
    surface: only the explicit scalar-function registry dispatches."""
    from spark_tpu.connect import proto

    # F.expr / F.col exist on the module but are session-side builders
    for name in ("expr", "col", "lit", "window", "udf"):
        with pytest.raises(ValueError, match="unknown function"):
            proto.decode_expr({"e": "fn", "name": name,
                               "args": [{"e": "lit", "value": "x"}]})
    # registry functions still decode
    e = proto.decode_expr({"e": "fn", "name": "upper",
                           "args": [{"e": "col", "name": "s"}]})
    assert e is not None

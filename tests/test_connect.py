"""Connect server: SQL over HTTP with Arrow IPC results (reference:
connector/connect SparkConnectService + thriftserver)."""

import pytest

from spark_tpu.connect import Client, ConnectServer


@pytest.fixture()
def server(spark):
    spark.createDataFrame(
        [{"k": i % 3, "v": i} for i in range(30)]
    ).createOrReplaceTempView("conn_t")
    srv = ConnectServer(spark, port=0).start()
    yield srv
    srv.stop()


def test_sql_roundtrip(server):
    c = Client(server.url)
    tbl = c.sql("select k, sum(v) as s from conn_t group by k order by k")
    rows = tbl.to_pylist()
    assert rows == [
        {"k": 0, "s": sum(range(0, 30, 3))},
        {"k": 1, "s": sum(range(1, 30, 3))},
        {"k": 2, "s": sum(range(2, 30, 3))}]


def test_tables_and_errors(server):
    c = Client(server.url)
    assert "conn_t" in c.tables()
    with pytest.raises(RuntimeError):
        c.sql("select * from does_not_exist")

"""Deterministic fault injection + the HBM-pressure degradation ladder
(spark_tpu/faults.py; reference chaos peers: FailureSuite.scala,
DAGSchedulerSuite's MockBackend killing executors mid-stage, and
TungstenAggregationIterator's sort-fallback under memory pressure).

The fault-matrix contract: with each injection point firing once
(``nth:1``), every golden query either returns results identical to the
no-fault run (recovered/degraded paths) or raises a typed, single-cause
error — no hangs, no silent wrong answers.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_tpu import faults, metrics, recovery, tracing
from spark_tpu.conf import RuntimeConf

_TEST_CONF_KEYS = tuple(
    f"spark.tpu.faultInjection.{p}" for p in faults.POINTS) + (
    "spark.tpu.faultInjection.hangSeconds",
    "spark.tpu.maxDeviceBatchBytes",
    "spark.tpu.chunkRows",
    "spark.tpu.chunkRetryAttempts",
    "spark.tpu.oomDegrade.floorBytes",
    "spark.tpu.pipelineDepth",
    "spark.stage.maxConsecutiveAttempts",
)


@pytest.fixture()
def fconf(spark):
    """The session conf with guaranteed cleanup: every fault-injection
    arm and tier knob is unset and the arming counters dropped, so a
    failing test cannot leak faults into the rest of the suite."""
    conf = spark.conf
    faults.reset(conf)
    yield conf
    for key in _TEST_CONF_KEYS:
        try:
            conf.unset(key)
        except KeyError:
            pass
    faults.reset(conf)


@pytest.fixture(scope="module")
def fact_parquet(spark, tmp_path_factory):
    """Integer-valued fact table: SUM/COUNT are exact in every tier, so
    chunked-vs-resident results compare with == (the cross-tier oracle
    the degradation tests need)."""
    rng = np.random.default_rng(7)
    n = 200_000
    tbl = pa.table({
        "k": pa.array(rng.integers(0, 50, n), pa.int64()),
        "v": pa.array(rng.integers(0, 1000, n), pa.int64()),
    })
    path = str(tmp_path_factory.mktemp("faults") / "fact.parquet")
    pq.write_table(tbl, path, row_group_size=20_000)
    return path


_GOLDEN = "SELECT k, SUM(v) AS s, COUNT(*) AS c FROM {t} GROUP BY k ORDER BY k"


def _golden(spark, path, view="fact_tbl"):
    spark.read.parquet(path).createOrReplaceTempView(view)
    query = _GOLDEN.format(t=view)
    return lambda: [r.asDict() for r in spark.sql(query).collect()]


def _kinds(n=4096):
    return [e["kind"] for e in metrics.recent(n)]


def _set_chunked(conf):
    conf.set("spark.tpu.maxDeviceBatchBytes", 1 << 19)
    conf.set("spark.tpu.chunkRows", 50_000)
    conf.set("spark.tpu.oomDegrade.floorBytes", 1 << 16)


# ---- spec grammar / arming mechanics ----------------------------------------


def test_parse_spec_validation():
    assert faults.parse_spec("none") is None
    assert faults.parse_spec("") is None
    s = faults.parse_spec("nth:3")
    assert s.mode == "nth" and s.k == 3 and s.kind == "transient"
    s = faults.parse_spec("nth:1:oom")
    assert s.kind == "oom"
    s = faults.parse_spec("prob:0.25:99:corrupt")
    assert s.mode == "prob" and s.p == 0.25 and s.seed == 99 \
        and s.kind == "corrupt"
    for bad in ("nth", "nth:x", "nth:1:bogus", "prob:0.5", "prob:p:1",
                "wat:1", "nth:1:2:3"):
        with pytest.raises(ValueError):
            faults.parse_spec(bad)


def test_nth_fires_exactly_once():
    conf = RuntimeConf({})
    conf.set("spark.tpu.faultInjection.execute.device", "nth:2")
    faults.inject("execute.device", conf)  # arrival 1: no fire
    with pytest.raises(faults.InjectedTransientError) as ei:
        faults.inject("execute.device", conf)  # arrival 2: fires
    assert "UNAVAILABLE" in str(ei.value)
    assert ei.value.point == "execute.device"
    for _ in range(5):  # never re-fires
        faults.inject("execute.device", conf)
    assert faults.fire_count(conf, "execute.device") == 1
    # changing the spec re-arms the point
    conf.set("spark.tpu.faultInjection.execute.device", "nth:1:corrupt")
    with pytest.raises(faults.InjectedCorruptionError):
        faults.inject("execute.device", conf)


def test_prob_spec_is_deterministic():
    def fires(conf):
        out = []
        for _ in range(20):
            try:
                faults.inject("execute.device", conf)
                out.append(False)
            except faults.InjectedFault:
                out.append(True)
        return out

    a, b = RuntimeConf({}), RuntimeConf({})
    for c in (a, b):
        c.set("spark.tpu.faultInjection.execute.device", "prob:0.5:1234")
    assert fires(a) == fires(b)  # same seed, same stream
    c = RuntimeConf({})
    c.set("spark.tpu.faultInjection.execute.device", "prob:0.0:1")
    assert fires(c) == [False] * 20
    c = RuntimeConf({})
    c.set("spark.tpu.faultInjection.execute.device", "prob:1.0:1")
    assert fires(c) == [True] * 20


def test_unknown_point_rejected():
    conf = RuntimeConf({})
    with pytest.raises(ValueError, match="unknown fault-injection point"):
        faults.inject("no.such.seam", conf)


def test_disarmed_inject_is_noop(fconf):
    faults.inject("execute.device", fconf)  # default spec: none
    assert faults.fire_count(fconf, "execute.device") == 0


# ---- fault matrix: pipeline seams (chunked tier) ----------------------------


@pytest.mark.parametrize("point", ["pipeline.decode", "pipeline.transfer"])
@pytest.mark.parametrize("kind", ["transient", "hang", "oom", "corrupt"])
def test_fault_matrix_pipeline(spark, fconf, fact_parquet, point, kind):
    run = _golden(spark, fact_parquet)
    _set_chunked(fconf)
    oracle = run()  # no-fault oracle under the same chunked conf
    metrics.reset()
    fconf.set("spark.tpu.faultInjection.hangSeconds", 0.02)
    fconf.set(f"spark.tpu.faultInjection.{point}", f"nth:2:{kind}")
    faults.reset(fconf)
    if kind == "corrupt":
        # unrecoverable by design: surfaces unretried as the typed error
        with pytest.raises(faults.InjectedCorruptionError, match="DATA_LOSS"):
            run()
        return
    got = run()
    assert got == oracle
    kinds = _kinds()
    assert "fault_injected" in kinds
    if kind in ("transient", "hang"):
        # absorbed by the per-chunk retry inside the pipeline producer
        assert "chunk_retry" in kinds and "fault_recovered" in kinds
    else:  # oom: replanned through the ladder at a halved budget
        assert "degraded_to_chunked" in kinds


# ---- fault matrix: whole-batch device execution -----------------------------


@pytest.mark.parametrize("kind", ["transient", "hang", "oom", "corrupt"])
def test_fault_matrix_execute_device(spark, fconf, fact_parquet, kind):
    run = _golden(spark, fact_parquet)
    oracle = run()  # resident no-fault oracle
    metrics.reset()
    fconf.set("spark.tpu.faultInjection.hangSeconds", 0.02)
    fconf.set("spark.tpu.faultInjection.execute.device", f"nth:1:{kind}")
    fconf.set("spark.tpu.chunkRows", 50_000)  # ladder's chunk size
    faults.reset(fconf)
    if kind == "corrupt":
        with pytest.raises(faults.InjectedCorruptionError, match="DATA_LOSS"):
            run()
        return
    got = run()
    assert got == oracle
    kinds = _kinds()
    assert "fault_injected" in kinds and "fault_recovered" in kinds
    if kind in ("transient", "hang"):
        assert "stage_retry" in kinds  # blind retry is right for these
    else:
        # OOM must NOT blind-retry the identical plan — it degrades
        assert "degraded_to_chunked" in kinds
        assert "stage_retry" not in kinds


def test_oom_degradation_ladder_whole_batch_to_chunked(
        spark, fconf, fact_parquet):
    """The acceptance path spelled out: an injected whole-batch OOM
    demonstrably re-executes via the chunked tier (degraded_to_chunked
    metric at a halved budget) with oracle-identical output, and the
    session budget is untouched afterwards."""
    run = _golden(spark, fact_parquet)
    oracle = run()
    metrics.reset()
    fconf.set("spark.tpu.faultInjection.execute.device", "nth:1:oom")
    fconf.set("spark.tpu.chunkRows", 50_000)
    faults.reset(fconf)
    assert run() == oracle
    degr = [e for e in metrics.recent(4096)
            if e["kind"] == "degraded_to_chunked"]
    assert degr and "RESOURCE_EXHAUSTED" in degr[0]["error"]
    rec = [e for e in metrics.recent(4096)
           if e["kind"] == "fault_recovered"
           and e.get("how") == "degraded_to_chunked"]
    assert rec and rec[0]["budget"] == degr[-1]["budget"]
    from spark_tpu.physical.chunked import MAX_DEVICE_BATCH_BYTES

    # the halved budget lived on a shadow conf, not the session
    assert fconf.get(MAX_DEVICE_BATCH_BYTES) == MAX_DEVICE_BATCH_BYTES.default
    # next run (no fault armed beyond the spent nth:1): resident again
    assert run() == oracle


def test_oom_ladder_gives_up_at_floor(spark, fconf, fact_parquet):
    """An OOM that persists in the chunked tier at every halved budget
    surfaces as a clean RuntimeError naming the floor, with the
    ladder's last OOM chained — never an unbounded loop."""
    run = _golden(spark, fact_parquet)
    # whole-batch OOMs once, then every chunked attempt OOMs too
    fconf.set("spark.tpu.faultInjection.execute.device", "nth:1:oom")
    fconf.set("spark.tpu.faultInjection.pipeline.transfer",
              "prob:1.0:1:oom")
    fconf.set("spark.tpu.maxDeviceBatchBytes", 1 << 22)
    fconf.set("spark.tpu.chunkRows", 50_000)
    fconf.set("spark.tpu.oomDegrade.floorBytes", 1 << 20)
    faults.reset(fconf)
    with pytest.raises(RuntimeError, match="floor") as ei:
        run()
    assert recovery.is_oom(ei.value.__cause__)


def test_oom_unchunkable_plan_surfaces_original(spark, fconf):
    """A plan with no file-backed scan (in-memory relation) cannot be
    chunked at ANY budget: the ladder surfaces the original typed OOM
    instead of a misleading 'degraded to the floor' error."""
    spark.createDataFrame([{"k": i % 3, "v": i} for i in range(100)]) \
        .createOrReplaceTempView("mem_tbl")
    fconf.set("spark.tpu.faultInjection.execute.device", "nth:1:oom")
    faults.reset(fconf)
    with pytest.raises(faults.InjectedOOMError, match="RESOURCE_EXHAUSTED"):
        spark.sql("SELECT k, SUM(v) AS s FROM mem_tbl GROUP BY k").collect()


def test_oom_degrade_disabled_surfaces_oom(spark, fconf, fact_parquet):
    run = _golden(spark, fact_parquet)
    fconf.set("spark.tpu.oomDegrade.enabled", False)
    fconf.set("spark.tpu.faultInjection.execute.device", "nth:1:oom")
    faults.reset(fconf)
    try:
        with pytest.raises(faults.InjectedOOMError):
            run()
    finally:
        fconf.unset("spark.tpu.oomDegrade.enabled")


# ---- pipeline per-chunk retry across depths ---------------------------------


@pytest.mark.parametrize("depth", [1, 2, 4])
def test_pipeline_chunk_retry_depth_sweep(spark, fconf, fact_parquet, depth):
    """A transient failure on one chunk's transfer costs one chunk
    retry — not the query — and the merged result stays byte-identical
    to the same-depth no-fault run."""
    run = _golden(spark, fact_parquet)
    _set_chunked(fconf)
    fconf.set("spark.tpu.pipelineDepth", depth)
    oracle = run()
    metrics.reset()
    fconf.set("spark.tpu.faultInjection.pipeline.transfer", "nth:2:transient")
    faults.reset(fconf)
    assert run() == oracle
    assert faults.fire_count(fconf, "pipeline.transfer") == 1
    kinds = _kinds()
    assert "chunk_retry" in kinds and "fault_recovered" in kinds
    # the whole query was NOT restarted for a one-chunk failure
    assert "stage_retry" not in kinds


def test_pipeline_retry_exhaustion_fails_cleanly(spark, fconf, fact_parquet):
    """Retries are bounded: a chunk that fails on every attempt relays
    the error instead of spinning (and the stage-retry wrapper's budget
    bounds the whole query)."""
    run = _golden(spark, fact_parquet)
    _set_chunked(fconf)
    fconf.set("spark.tpu.faultInjection.pipeline.transfer",
              "prob:1.0:7:transient")
    fconf.set("spark.tpu.chunkRetryAttempts", 2)
    fconf.set("spark.stage.maxConsecutiveAttempts", 2)
    faults.reset(fconf)
    with pytest.raises(RuntimeError, match="consecutive attempts"):
        run()


def test_chunk_pipeline_decode_failure_not_retried_mid_stream():
    """A REAL decode failure (the source iterator itself raised) is not
    retryable — a generator that raised is exhausted, and retrying
    next() would silently truncate the stream. Only injected decode
    faults (which fire before the source is touched) retry."""
    from spark_tpu.metrics import PipelineStats
    from spark_tpu.physical.pipeline import ChunkPipeline

    def source():
        yield 1
        raise ConnectionResetError("mid-stream")  # transient by type

    pipe = ChunkPipeline(source(), lambda x: x, depth=1,
                         byte_budget=1 << 20, stats=PipelineStats())
    with pytest.raises(ConnectionResetError):
        list(pipe)


def test_chunk_pipeline_prepare_retry_preserves_order():
    """Prepare-phase retries re-use the in-hand item: output order and
    content match the no-fault run exactly, at depth 0 and threaded."""
    from spark_tpu.metrics import PipelineStats
    from spark_tpu.physical.pipeline import ChunkPipeline

    conf = RuntimeConf({})
    conf.set("spark.tpu.faultInjection.pipeline.transfer", "nth:3")
    for depth in (0, 2):
        faults.reset(conf)
        conf.set("spark.tpu.faultInjection.pipeline.transfer", "nth:3")
        pipe = ChunkPipeline(iter(range(6)), lambda x: x * 10, depth=depth,
                             byte_budget=1 << 20, stats=PipelineStats(),
                             conf=conf)
        assert list(pipe) == [0, 10, 20, 30, 40, 50]
        assert faults.fire_count(conf, "pipeline.transfer") == 1


# ---- fault matrix: the all-to-all exchange ----------------------------------


def _sort_plan(colname, n=512):
    from spark_tpu.columnar.arrow import from_arrow
    from spark_tpu.expr import expressions as E
    from spark_tpu.plan import logical as L

    tbl = pa.table({colname: pa.array((np.arange(n) * 37) % 211)})
    return L.Sort((E.SortOrder(E.Col(colname), True),),
                  L.Relation(from_arrow(tbl)))


@pytest.fixture(scope="module")
def mesh_ex():
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh

    return MeshExecutor(make_mesh(8))


@pytest.mark.parametrize("kind", ["transient", "hang", "oom", "corrupt"])
def test_fault_matrix_exchange(spark, fconf, mesh_ex, kind):
    """The exchange seam fires at trace time. Each cell sorts a
    distinct column name so the mesh executor re-traces (a cached
    program never re-runs the Python-level collective builder) —
    transient kinds recover through the stage-retry wrapper (a failed
    trace is not cached), non-recoverable kinds surface typed."""
    colname = f"x_{kind}"
    fconf.set("spark.tpu.faultInjection.hangSeconds", 0.02)
    fconf.set("spark.tpu.faultInjection.exchange.all_to_all",
              f"nth:1:{kind}")
    faults.reset(fconf)
    if kind in ("transient", "hang"):
        got = recovery.run_stage_with_recovery(
            lambda: mesh_ex.execute_logical(_sort_plan(colname)),
            conf=fconf, label="exchange")
        vals = [r[colname] for r in got.to_pylist()]
        assert vals == sorted(vals) and len(vals) == 512
        assert faults.fire_count(fconf, "exchange.all_to_all") == 1
    elif kind == "oom":
        # no mesh-level ladder (the collective's capacity is the plan):
        # a clean typed error, never a silent wrong answer
        with pytest.raises(faults.InjectedOOMError):
            mesh_ex.execute_logical(_sort_plan(colname))
    else:
        with pytest.raises(faults.InjectedCorruptionError):
            mesh_ex.execute_logical(_sort_plan(colname))


# ---- fault matrix: streaming micro-batch commit -----------------------------


@pytest.mark.parametrize("kind", ["transient", "hang", "oom", "corrupt"])
def test_streaming_commit_crash_replays_from_wal(spark, fconf, tmp_path,
                                                 kind):
    """A crash at the commit seam — whatever killed it — loses nothing:
    the restarted query replays the WAL'd offsets and converges to the
    same state, and the replay is visible as a fault_recovered event."""
    from spark_tpu.api import functions as F
    from spark_tpu.streaming import MemoryStream

    expected_exc = {
        "transient": faults.InjectedTransientError,
        "hang": faults.InjectedDeadlineError,
        "oom": faults.InjectedOOMError,
        "corrupt": faults.InjectedCorruptionError,
    }[kind]
    ckpt = str(tmp_path / "fck")
    src = MemoryStream(pa.schema([("k", pa.string()), ("v", pa.int64())]))
    agg = spark.readStream.load(src).groupBy("k").agg(F.sum("v").alias("s"))
    q = agg.writeStream.outputMode("complete").queryName("fstr1") \
        .option("checkpointLocation", ckpt).start()
    src.add_data([{"k": "a", "v": 5}])
    q.process_all_available()

    fconf.set("spark.tpu.faultInjection.hangSeconds", 0.02)
    fconf.set("spark.tpu.faultInjection.streaming.commit", f"nth:1:{kind}")
    faults.reset(fconf)
    src.add_data([{"k": "a", "v": 7}, {"k": "b", "v": 1}])
    with pytest.raises(expected_exc):
        q.process_all_available()
    q.stop()
    fconf.unset("spark.tpu.faultInjection.streaming.commit")

    metrics.reset()
    q2 = agg.writeStream.outputMode("complete").queryName("fstr2") \
        .option("checkpointLocation", ckpt).start()
    q2.process_all_available()
    rows = {r.k: r.s for r in spark.sql("select * from fstr2").collect()}
    assert rows == {"a": 12, "b": 1}
    assert any(e["kind"] == "fault_recovered"
               and e.get("how") == "wal_replay" for e in metrics.recent(100))
    q2.stop()


def test_streaming_append_no_duplicate_after_commit_crash(
        spark, fconf, tmp_path):
    """Non-agg append output is only published AFTER the commit, so the
    crash + WAL replay emits the batch exactly once."""
    from spark_tpu.api import functions as F
    from spark_tpu.streaming import MemoryStream

    ckpt = str(tmp_path / "fck2")
    src = MemoryStream(pa.schema([("v", pa.int64())]))
    df = spark.readStream.load(src).select((F.col("v") * 10).alias("w"))
    q = df.writeStream.outputMode("append").queryName("fap1") \
        .option("checkpointLocation", ckpt).start()
    src.add_data([{"v": 1}])
    q.process_all_available()

    fconf.set("spark.tpu.faultInjection.streaming.commit", "nth:1:corrupt")
    faults.reset(fconf)
    src.add_data([{"v": 2}])
    with pytest.raises(faults.InjectedCorruptionError):
        q.process_all_available()
    q.stop()
    fconf.unset("spark.tpu.faultInjection.streaming.commit")

    q2 = df.writeStream.outputMode("append").queryName("fap2") \
        .option("checkpointLocation", ckpt).start()
    q2.process_all_available()
    vals = sorted(r.w for r in spark.sql("select * from fap2").collect())
    assert vals == [20]  # the replayed batch, exactly once — no [20, 20]
    q2.stop()


# ---- fault matrix: connect round-trip ---------------------------------------


@pytest.fixture()
def connect_srv(spark):
    from spark_tpu.connect.server import ConnectServer

    spark.createDataFrame([{"x": 1, "y": "a"}, {"x": 2, "y": "b"}]) \
        .createOrReplaceTempView("fconn_tv")
    srv = ConnectServer(spark).start()
    yield srv
    srv.stop()


@pytest.mark.parametrize("kind", ["transient", "oom", "corrupt"])
def test_fault_matrix_connect(spark, fconf, connect_srv, kind):
    from spark_tpu.connect.server import Client

    cli = Client(connect_srv.url, timeout=10.0)
    assert cli.sql("SELECT x FROM fconn_tv ORDER BY x") \
        .column("x").to_pylist() == [1, 2]
    fconf.set("spark.tpu.faultInjection.connect.request", f"nth:1:{kind}")
    faults.reset(fconf)
    marker = {"transient": "UNAVAILABLE", "oom": "RESOURCE_EXHAUSTED",
              "corrupt": "DATA_LOSS"}[kind]
    with pytest.raises(RuntimeError) as ei:
        cli.sql("SELECT x FROM fconn_tv")
    # typed marker AND the server-side traceback in the raised error
    assert marker in str(ei.value)
    assert "server traceback" in str(ei.value)
    fconf.unset("spark.tpu.faultInjection.connect.request")
    # the server survives: next request succeeds
    assert cli.sql("SELECT x FROM fconn_tv ORDER BY x") \
        .column("x").to_pylist() == [1, 2]


def test_connect_client_timeout_on_hung_server(spark, fconf, connect_srv):
    """An injected hang longer than the client deadline surfaces as a
    DEADLINE_EXCEEDED timeout instead of blocking forever (the
    satellite: urllib had no timeout at all)."""
    from spark_tpu.connect.server import Client

    fconf.set("spark.tpu.faultInjection.connect.request", "nth:1:hang")
    fconf.set("spark.tpu.faultInjection.hangSeconds", 3.0)
    faults.reset(fconf)
    cli = Client(connect_srv.url, timeout=0.3)
    with pytest.raises(RuntimeError, match="DEADLINE_EXCEEDED"):
        cli.sql("SELECT x FROM fconn_tv")


def test_connect_health_carries_heartbeat(spark):
    from spark_tpu.connect.server import Client, ConnectServer

    mon = recovery.HeartbeatMonitor(interval_s=30).start()
    srv = ConnectServer(spark, heartbeat=mon).start()
    try:
        h = Client(srv.url, timeout=10.0).health()
        assert h["status"] == "ok"
        assert h["heartbeat"]["last_ok"] is not None
        assert h["heartbeat"]["interval_s"] == 30
    finally:
        srv.stop()
        mon.stop()


def test_connect_health_without_heartbeat(spark, connect_srv):
    from spark_tpu.connect.server import Client

    h = Client(connect_srv.url, timeout=10.0).health()
    assert h["status"] == "ok" and h["heartbeat"] is None


# ---- observability ----------------------------------------------------------


def test_fault_profile_rollup(spark, fconf, fact_parquet):
    run = _golden(spark, fact_parquet)
    run()
    metrics.reset()
    fconf.set("spark.tpu.faultInjection.execute.device", "nth:1:transient")
    faults.reset(fconf)
    run()
    prof = tracing.fault_profile()
    assert prof["fault_injected"]["count"] == 1
    assert prof["fault_injected"]["points"] == {"execute.device": 1}
    assert prof["stage_retry"]["count"] == 1
    assert prof["fault_recovered"]["count"] == 1
    text = tracing.format_fault_profile(prof)
    assert "fault_injected: 1" in text and "execute.device=1" in text


def test_fault_events_reach_event_log(spark, fconf, fact_parquet, tmp_path):
    """Injected faults land in the JSONL event log, so post-mortem
    tooling (history/bench) sees them without live metrics access."""
    import json
    import os

    run = _golden(spark, fact_parquet)
    log = str(tmp_path / "events")
    fconf.set("spark.eventLog.dir", log)
    try:
        fconf.set("spark.tpu.faultInjection.execute.device",
                  "nth:1:transient")
        faults.reset(fconf)
        run()
        files = [os.path.join(log, f) for f in os.listdir(log)]
        recorded = []
        for f in files:
            with open(f) as fh:
                recorded += [json.loads(line) for line in fh]
        kinds = {e.get("kind") for e in recorded}
        assert "fault_injected" in kinds and "fault_recovered" in kinds
    finally:
        fconf.unset("spark.eventLog.dir")

"""Tree-wide concurrency analyzer (spark_tpu/analysis/concurrency.py)
+ its CLI (tools/lint_concurrency.py).

Coverage contract (mirrors tests/test_analysis.py for the invariant
linter):

- the linter is CLEAN on this tree (zero findings with the checked-in
  [tool.lint-concurrency] config),
- each CONC-* rule fires on a seeded violation with exactly its own
  code — rank inversion, unranked cycle, unlocked mutation of shared
  state, blocking call under a held lock, Condition.wait outside a
  predicate loop,
- the exemption table cannot rot: blank justifications and stale keys
  are themselves findings,
- the CLI exits 0 on the tree, alongside lint_invariants (both run in
  tier-1 through this file).
"""

import os
import subprocess
import sys
import textwrap

import pytest

from spark_tpu import locks
from spark_tpu.analysis import concurrency

pytestmark = pytest.mark.analysis

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "tools"))
import lint_concurrency  # noqa: E402


def _codes(src, **kw):
    findings = concurrency.analyze_sources(
        {"x.py": textwrap.dedent(src)}, **kw)
    return [d.code for d in findings], findings


# ---- clean on this tree -----------------------------------------------------


def test_conc_lint_clean_on_tree():
    findings = lint_concurrency.run_lint()
    assert findings == [], "\n".join(d.format() for d in findings)


def test_lock_registry_sane():
    assert locks.LOCK_RANKS, "registry must not be empty"
    for name, rank in locks.LOCK_RANKS.items():
        assert isinstance(name, str) and name
        assert isinstance(rank, int) and rank > 0
    # every alias in the checked-in config points at a registered name
    cfg = lint_concurrency._load_config()
    for key, target in cfg["aliases"].items():
        assert target in locks.LOCK_RANKS, \
            f"alias {key!r} -> unregistered lock {target!r}"


# ---- seeded violations: each rule fires exactly its code --------------------


def test_seeded_rank_inversion_fires():
    codes, findings = _codes("""\
        from spark_tpu import locks

        class Store:
            def __init__(self):
                self._mgr_lock = locks.named_rlock("storage.unified")
                self._reg_lock = locks.named_lock(
                    "session.cache.registry")

            def bad(self):
                with self._mgr_lock:
                    with self._reg_lock:
                        return 1
        """)
    assert codes == ["CONC-ORDER-CYCLE"], findings
    assert "inverts" in findings[0].message


def test_seeded_unranked_cycle_fires():
    codes, findings = _codes("""\
        import threading

        _A_LOCK = threading.Lock()
        _B_LOCK = threading.Lock()

        def f1():
            with _A_LOCK:
                with _B_LOCK:
                    pass

        def f2():
            with _B_LOCK:
                with _A_LOCK:
                    pass
        """)
    assert codes == ["CONC-ORDER-CYCLE"], findings
    assert "cycle" in findings[0].message


def test_seeded_unlocked_mutation_fires():
    codes, findings = _codes("""\
        import threading

        _LOCK = threading.Lock()
        _TABLE = {}

        def locked_put(k, v):
            with _LOCK:
                _TABLE[k] = v

        def bare_drop(k):
            _TABLE.pop(k, None)
        """)
    assert codes == ["CONC-UNLOCKED-MUT"], findings
    assert "bare_drop" in findings[0].message


def test_seeded_blocking_under_lock_fires():
    codes, findings = _codes("""\
        import threading
        import time

        _LOCK = threading.Lock()

        def slow():
            with _LOCK:
                time.sleep(0.1)
        """)
    assert codes == ["CONC-BLOCKING-HELD"], findings
    assert "time.sleep()" in findings[0].message


def test_seeded_bare_wait_fires_and_looped_wait_passes():
    codes, findings = _codes("""\
        import threading

        _COND = threading.Condition()

        def bad_wait():
            with _COND:
                _COND.wait()

        def good_wait(pred):
            with _COND:
                while not pred():
                    _COND.wait()
        """)
    assert codes == ["CONC-WAIT-NOLOOP"], findings
    assert findings[0].node == "x.py:7"


def test_exemption_suppresses_blocking_finding():
    src = """\
        import threading
        import time

        _LOCK = threading.Lock()

        def slow():
            with _LOCK:
                time.sleep(0.1)
        """
    codes, _ = _codes(src, exempt_blocking={"x.py::slow": "seeded"})
    assert codes == []


# ---- exemption-table hygiene ------------------------------------------------


def _mini_config(**over):
    cfg = {"paths": ["spark_tpu/analysis"], "exclude": [],
           "aliases": {}, "exempt_unlocked": {}, "exempt_blocking": {}}
    cfg.update(over)
    return cfg


def test_blank_justification_is_a_finding():
    cfg = _mini_config(exempt_blocking={
        "spark_tpu/analysis/concurrency.py::whatever": "   "})
    codes = [d.code for d in lint_concurrency.run_lint(config=cfg)]
    assert codes == ["CONC-EXEMPT-UNJUSTIFIED"]


def test_stale_exemption_key_is_a_finding():
    cfg = _mini_config(exempt_unlocked={
        "spark_tpu/analysis/deleted_module.py::_X": "was real once"})
    codes = [d.code for d in lint_concurrency.run_lint(config=cfg)]
    assert codes == ["CONC-EXEMPT-STALE"]


# ---- CLI: both linters run in tier-1 and exit 0 -----------------------------


@pytest.mark.parametrize("tool", ["lint_concurrency.py",
                                  "lint_invariants.py"])
def test_lint_cli_exits_zero(tool):
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools", tool)],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout

"""History reader over the JSONL event log (reference role:
ui/SparkUI.scala:40 + deploy/history/FsHistoryProvider.scala — here a
web-stack-free text/HTML renderer, spark_tpu/history.py)."""

import subprocess
import sys


def test_history_summarize_and_render(spark, tmp_path):
    from spark_tpu import history

    logdir = tmp_path / "events"
    logdir.mkdir()
    spark.conf.set("spark.eventLog.dir", str(logdir))
    try:
        df = spark.createDataFrame(
            [{"k": i % 3, "v": float(i)} for i in range(64)])
        df.groupBy("k").sum("v").collect()
        df.filter("v > 10").count()
    finally:
        spark.conf.unset("spark.eventLog.dir")

    queries = history.summarize(str(logdir))
    assert len(queries) >= 2
    assert any(q["stages"] for q in queries)
    text = history.render_text(queries)
    assert "total ms" in text and "ms" in text
    html = history.render_html(queries)
    assert html.startswith("<html>") and "details" in html

    out = tmp_path / "report.html"
    rc = subprocess.run(
        [sys.executable, "-m", "spark_tpu.history", str(logdir),
         "--html", str(out)],
        capture_output=True, text=True, timeout=120,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert rc.returncode == 0, rc.stderr
    assert out.exists() and out.read_text().startswith("<html>")

"""SQL three-valued-logic corner cases in subquery rewriting (reference:
optimizer/subquery.scala RewritePredicateSubquery null-aware anti join,
RewriteCorrelatedScalarSubquery COUNT handling)."""



def test_not_in_with_null_in_subquery(spark):
    spark.createDataFrame(
        [{"x": 1}, {"x": 2}, {"x": 3}]).createOrReplaceTempView("tvl_l")
    spark.createDataFrame(
        [{"y": 1}, {"y": None}]).createOrReplaceTempView("tvl_r")
    # NULL in the subquery: NOT IN is never TRUE -> empty result
    rows = spark.sql(
        "select x from tvl_l where x not in (select y from tvl_r)").collect()
    assert rows == []


def test_not_in_without_nulls(spark):
    spark.createDataFrame(
        [{"x": 1}, {"x": 2}, {"x": 3}]).createOrReplaceTempView("tv2_l")
    spark.createDataFrame([{"y": 1}]).createOrReplaceTempView("tv2_r")
    rows = spark.sql(
        "select x from tv2_l where x not in (select y from tv2_r)").collect()
    assert sorted(r.x for r in rows) == [2, 3]


def test_not_in_empty_subquery(spark):
    spark.createDataFrame(
        [{"x": 1}, {"x": None}]).createOrReplaceTempView("tv3_l")
    spark.createDataFrame([{"y": 5}]).createOrReplaceTempView("tv3_r")
    # empty subquery: everything qualifies, even NULL probes
    rows = spark.sql(
        "select x from tv3_l where x not in "
        "(select y from tv3_r where y > 100)").collect()
    assert len(rows) == 2


def test_not_in_null_probe(spark):
    spark.createDataFrame(
        [{"x": 1}, {"x": None}]).createOrReplaceTempView("tv4_l")
    spark.createDataFrame([{"y": 5}]).createOrReplaceTempView("tv4_r")
    # NULL probe vs non-empty subquery -> UNKNOWN -> dropped
    rows = spark.sql(
        "select x from tv4_l where x not in (select y from tv4_r)").collect()
    assert [r.x for r in rows] == [1]


def test_scalar_subquery_empty_yields_null(spark):
    spark.createDataFrame([{"x": 1}, {"x": 2}]).createOrReplaceTempView("sv_l")
    spark.createDataFrame([{"y": 9}]).createOrReplaceTempView("sv_r")
    rows = spark.sql(
        "select x, (select y from sv_r where y > 100) as s from sv_l"
    ).collect()
    assert len(rows) == 2 and all(r.s is None for r in rows)


def test_correlated_count_empty_group_is_zero(spark):
    spark.createDataFrame(
        [{"k": 1}, {"k": 2}]).createOrReplaceTempView("cc_l")
    spark.createDataFrame(
        [{"k": 1, "v": 10}]).createOrReplaceTempView("cc_r")
    rows = spark.sql(
        "select k, (select count(*) from cc_r where cc_r.k = cc_l.k) as c "
        "from cc_l order by k").collect()
    assert [(r.k, r.c) for r in rows] == [(1, 1), (2, 0)]


def test_not_in_null_literal_probe(spark):
    spark.createDataFrame(
        [{"k": 1}, {"k": 2}, {"k": 3}]).createOrReplaceTempView("tv5_l")
    spark.createDataFrame([{"y": 7}]).createOrReplaceTempView("tv5_r")
    # NULL NOT IN (non-empty) is UNKNOWN for every row -> empty result
    rows = spark.sql(
        "select k from tv5_l where null not in (select y from tv5_r)"
    ).collect()
    assert rows == []


def test_correlated_not_in_null_probe(spark):
    spark.createDataFrame(
        [{"k": 1, "x": 5}, {"k": 1, "x": None}, {"k": 2, "x": None}]
    ).createOrReplaceTempView("tv6_l")
    spark.createDataFrame(
        [{"k": 1, "y": 9}]).createOrReplaceTempView("tv6_r")
    # (k=1, x=5): 5 != 9 -> TRUE, kept. (k=1, x=NULL): group non-empty ->
    # UNKNOWN, dropped. (k=2, x=NULL): group empty -> TRUE, kept.
    rows = spark.sql(
        "select k, x from tv6_l where x not in "
        "(select y from tv6_r where tv6_r.k = tv6_l.k)").collect()
    assert sorted([(r.k, r.x) for r in rows],
                  key=lambda t: (t[0], t[1] is None)) == [(1, 5), (2, None)]

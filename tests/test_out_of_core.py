"""Out-of-HBM chunked execution + skew handling (reference:
ExternalSorter.scala:93 spill, AggUtils map-side combine,
adaptive/OptimizeSkewedJoin.scala)."""

import pytest

from spark_tpu.api import functions as F


@pytest.fixture()
def big_parquet(spark, tmp_path):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(11)
    n = 200_000
    tbl = pa.table({
        "k": pa.array(rng.integers(0, 1000, n), pa.int64()),
        "v": pa.array(rng.random(n)),
        "w": pa.array(rng.integers(0, 100, n), pa.int64()),
    })
    path = str(tmp_path / "big.parquet")
    pq.write_table(tbl, path)
    return path, tbl


@pytest.mark.slow
def test_chunked_aggregation_matches_materialized(spark, big_parquet):
    path, tbl = big_parquet
    df = spark.read.parquet(path)
    agg = df.groupBy("k").agg(F.count("v").alias("n"),
                              F.sum("v").alias("s"),
                              F.min("w").alias("lo"),
                              F.max("w").alias("hi"),
                              F.avg("v").alias("a"))
    want = {r.k: (r.n, r.s, r.lo, r.hi, r.a) for r in agg.collect()}

    # force out-of-HBM: tiny budget + small chunks -> many partial passes
    spark.conf.set("spark.tpu.maxDeviceBatchBytes", 1024)
    spark.conf.set("spark.tpu.chunkRows", 32_768)
    try:
        from spark_tpu import metrics

        metrics.reset()
        got = {r.k: (r.n, r.s, r.lo, r.hi, r.a) for r in agg.collect()}
        chunk_evs = [e for e in metrics.recent(500)
                     if e["kind"] == "chunked_agg"]
        assert chunk_evs and chunk_evs[-1]["chunks"] >= 6
    finally:
        spark.conf.unset("spark.tpu.maxDeviceBatchBytes")
        spark.conf.unset("spark.tpu.chunkRows")
    assert set(got) == set(want)
    for k in want:
        assert got[k][0] == want[k][0]
        assert got[k][2:4] == want[k][2:4]
        assert got[k][1] == pytest.approx(want[k][1], rel=1e-9)
        assert got[k][4] == pytest.approx(want[k][4], rel=1e-9)


def test_chunked_with_filter_and_order(spark, big_parquet):
    path, _ = big_parquet
    df = spark.read.parquet(path)
    q = (df.filter(F.col("w") < 50).groupBy("k")
         .agg(F.count("v").alias("n")).orderBy(F.desc("n"), "k").limit(5))
    want = [(r.k, r.n) for r in q.collect()]
    spark.conf.set("spark.tpu.maxDeviceBatchBytes", 1024)
    try:
        got = [(r.k, r.n) for r in q.collect()]
    finally:
        spark.conf.unset("spark.tpu.maxDeviceBatchBytes")
    assert got == want


def test_global_agg_chunked(spark, big_parquet):
    path, tbl = big_parquet
    df = spark.read.parquet(path)
    q = df.agg(F.count("v").alias("n"), F.sum("w").alias("s"))
    want = (tbl.num_rows, sum(tbl.column("w").to_pylist()))
    spark.conf.set("spark.tpu.maxDeviceBatchBytes", 1024)
    try:
        r = q.collect()[0]
    finally:
        spark.conf.unset("spark.tpu.maxDeviceBatchBytes")
    assert (r.n, r.s) == want


def test_skewed_aggregation_map_side_combine(spark):
    """90% of rows share one key: map-side combine collapses the hot key
    to one row per device before the exchange."""
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh
    from spark_tpu.plan import logical as L
    from spark_tpu.expr import expressions as E

    n = 10_000
    rows = [{"k": (7 if i % 10 else i % 97), "v": 1} for i in range(n)]
    df = spark.createDataFrame(rows)
    plan = L.Aggregate((E.Col("k"),),
                       (E.Col("k"), E.Alias(E.Count(None), "n"),
                        E.Alias(E.Sum(E.Col("v")), "s")), df._plan)
    ex = MeshExecutor(make_mesh(8))
    got = {r["k"]: (r["n"], r["s"]) for r in
           ex.execute_logical(plan).to_pylist()}
    want: dict = {}
    for r in rows:
        c, s = want.get(r["k"], (0, 0))
        want[r["k"]] = (c + 1, s + r["v"])
    assert got == want


def test_skewed_join_completes(spark):
    """A 90%-one-key join completes on the mesh (capacity headroom +
    post-stage compaction absorb the hot partition)."""
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh
    from spark_tpu.plan import logical as L
    from spark_tpu.expr import expressions as E

    fact = spark.createDataFrame(
        [{"k": (1 if i % 10 else i % 50), "v": i} for i in range(5000)])
    dim = spark.createDataFrame([{"k": i, "w": i * 2} for i in range(50)])
    plan = L.Aggregate(
        (), (E.Alias(E.Count(None), "n"), E.Alias(E.Sum(E.Col("w")), "s")),
        L.Join(fact._plan, dim._plan, "inner",
               (E.Col("k"),), (E.Col("k"),)))
    ex = MeshExecutor(make_mesh(8), broadcast_threshold=1)  # force exchange
    r = ex.execute_logical(plan).to_pylist()[0]
    assert r["n"] == 5000
    want_s = sum((1 if i % 10 else i % 50) * 2 for i in range(5000))
    assert r["s"] == want_s

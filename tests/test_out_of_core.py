"""Out-of-HBM chunked execution + skew handling (reference:
ExternalSorter.scala:93 spill, AggUtils map-side combine,
adaptive/OptimizeSkewedJoin.scala)."""

import pytest

from spark_tpu.api import functions as F


@pytest.fixture()
def big_parquet(spark, tmp_path):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(11)
    n = 200_000
    tbl = pa.table({
        "k": pa.array(rng.integers(0, 1000, n), pa.int64()),
        "v": pa.array(rng.random(n)),
        "w": pa.array(rng.integers(0, 100, n), pa.int64()),
    })
    path = str(tmp_path / "big.parquet")
    pq.write_table(tbl, path)
    return path, tbl


@pytest.mark.slow
def test_chunked_aggregation_matches_materialized(spark, big_parquet):
    path, tbl = big_parquet
    df = spark.read.parquet(path)
    agg = df.groupBy("k").agg(F.count("v").alias("n"),
                              F.sum("v").alias("s"),
                              F.min("w").alias("lo"),
                              F.max("w").alias("hi"),
                              F.avg("v").alias("a"))
    want = {r.k: (r.n, r.s, r.lo, r.hi, r.a) for r in agg.collect()}

    # force out-of-HBM: tiny budget + small chunks -> many partial passes
    spark.conf.set("spark.tpu.maxDeviceBatchBytes", 1024)
    spark.conf.set("spark.tpu.chunkRows", 32_768)
    try:
        from spark_tpu import metrics

        metrics.reset()
        got = {r.k: (r.n, r.s, r.lo, r.hi, r.a) for r in agg.collect()}
        chunk_evs = [e for e in metrics.recent(500)
                     if e["kind"] == "chunked_agg"]
        assert chunk_evs and chunk_evs[-1]["chunks"] >= 6
    finally:
        spark.conf.unset("spark.tpu.maxDeviceBatchBytes")
        spark.conf.unset("spark.tpu.chunkRows")
    assert set(got) == set(want)
    for k in want:
        assert got[k][0] == want[k][0]
        assert got[k][2:4] == want[k][2:4]
        assert got[k][1] == pytest.approx(want[k][1], rel=1e-9)
        assert got[k][4] == pytest.approx(want[k][4], rel=1e-9)


def test_chunked_with_filter_and_order(spark, big_parquet):
    path, _ = big_parquet
    df = spark.read.parquet(path)
    q = (df.filter(F.col("w") < 50).groupBy("k")
         .agg(F.count("v").alias("n")).orderBy(F.desc("n"), "k").limit(5))
    want = [(r.k, r.n) for r in q.collect()]
    spark.conf.set("spark.tpu.maxDeviceBatchBytes", 1024)
    try:
        got = [(r.k, r.n) for r in q.collect()]
    finally:
        spark.conf.unset("spark.tpu.maxDeviceBatchBytes")
    assert got == want


def test_global_agg_chunked(spark, big_parquet):
    path, tbl = big_parquet
    df = spark.read.parquet(path)
    q = df.agg(F.count("v").alias("n"), F.sum("w").alias("s"))
    want = (tbl.num_rows, sum(tbl.column("w").to_pylist()))
    spark.conf.set("spark.tpu.maxDeviceBatchBytes", 1024)
    try:
        r = q.collect()[0]
    finally:
        spark.conf.unset("spark.tpu.maxDeviceBatchBytes")
    assert (r.n, r.s) == want


def test_skewed_aggregation_map_side_combine(spark):
    """90% of rows share one key: map-side combine collapses the hot key
    to one row per device before the exchange."""
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh
    from spark_tpu.plan import logical as L
    from spark_tpu.expr import expressions as E

    n = 10_000
    rows = [{"k": (7 if i % 10 else i % 97), "v": 1} for i in range(n)]
    df = spark.createDataFrame(rows)
    plan = L.Aggregate((E.Col("k"),),
                       (E.Col("k"), E.Alias(E.Count(None), "n"),
                        E.Alias(E.Sum(E.Col("v")), "s")), df._plan)
    ex = MeshExecutor(make_mesh(8))
    got = {r["k"]: (r["n"], r["s"]) for r in
           ex.execute_logical(plan).to_pylist()}
    want: dict = {}
    for r in rows:
        c, s = want.get(r["k"], (0, 0))
        want[r["k"]] = (c + 1, s + r["v"])
    assert got == want


def test_skewed_join_completes(spark):
    """A 90%-one-key join completes on the mesh (capacity headroom +
    post-stage compaction absorb the hot partition)."""
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh
    from spark_tpu.plan import logical as L
    from spark_tpu.expr import expressions as E

    fact = spark.createDataFrame(
        [{"k": (1 if i % 10 else i % 50), "v": i} for i in range(5000)])
    dim = spark.createDataFrame([{"k": i, "w": i * 2} for i in range(50)])
    plan = L.Aggregate(
        (), (E.Alias(E.Count(None), "n"), E.Alias(E.Sum(E.Col("w")), "s")),
        L.Join(fact._plan, dim._plan, "inner",
               (E.Col("k"),), (E.Col("k"),)))
    ex = MeshExecutor(make_mesh(8), broadcast_threshold=1)  # force exchange
    r = ex.execute_logical(plan).to_pylist()[0]
    assert r["n"] == 5000
    want_s = sum((1 if i % 10 else i % 50) * 2 for i in range(5000))
    assert r["s"] == want_s


@pytest.fixture()
def join_parquet(spark, tmp_path):
    """fact (200k rows, keys 0..999) + dim (keys 0..99 only: the
    sidecar's key set filters 90% of fact rows host-side)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(12)
    n = 200_000
    fact = pa.table({
        "k": pa.array(rng.integers(0, 1000, n), pa.int64()),
        "v": pa.array(rng.integers(0, 100, n), pa.int64()),
    })
    # NON-contiguous keys (0,10,..,990): min/max scan pruning cannot
    # help, so row drops must come from the membership filter
    dim = pa.table({
        "dk": pa.array(np.arange(100) * 10, pa.int64()),
        "w": pa.array(np.arange(100) * 2, pa.int64()),
    })
    fp, dp = str(tmp_path / "fact.parquet"), str(tmp_path / "dim.parquet")
    pq.write_table(fact, fp)
    pq.write_table(dim, dp)
    spark.read.parquet(fp).createOrReplaceTempView("oc_fact")
    spark.read.parquet(dp).createOrReplaceTempView("oc_dim")
    return fact, dim


def _chunk_events(kind):
    from spark_tpu import metrics

    return [e for e in metrics.recent(500) if e["kind"] == kind]


def test_streamed_join_aggregation(spark, join_parquet):
    """Tier 2: big fact streams through the join; dim pre-materializes
    once; the sidecar's key set drops non-matching fact rows host-side
    before they ship to device."""
    from spark_tpu import metrics

    sql = ("select k % 10 as g, sum(v * w) as s, count(*) as n "
           "from oc_fact join oc_dim on k = dk group by k % 10 "
           "order by g")
    want = [(r.g, r.s, r.n) for r in spark.sql(sql).collect()]
    assert want  # sanity: the resident run produced rows
    # dim (~1.7 KB) stays under budget; fact (~3.4 MB) chunks
    spark.conf.set("spark.tpu.maxDeviceBatchBytes", 100_000)
    spark.conf.set("spark.tpu.chunkRows", 32_768)
    try:
        metrics.reset()
        got = [(r.g, r.s, r.n) for r in spark.sql(sql).collect()]
        evs = _chunk_events("chunked_agg")
        assert evs and evs[-1]["chunks"] >= 2
        assert evs[-1]["sidecars"] == 1
        assert evs[-1]["key_filters"] == 1
        # keys 100..999 never ship: ~90% dropped host-side
        assert evs[-1]["rows_kept"] < 0.2 * evs[-1]["rows_in"]
    finally:
        spark.conf.unset("spark.tpu.maxDeviceBatchBytes")
        spark.conf.unset("spark.tpu.chunkRows")
    assert got == want


def test_streamed_left_join_no_filter(spark, join_parquet):
    """Left-outer keeps unmatched streamed rows, so the host-side key
    filter must NOT engage."""
    from spark_tpu import metrics

    sql = ("select count(*) as n, count(w) as m, sum(v) as s "
           "from oc_fact left join oc_dim on k = dk")
    want = [(r.n, r.m, r.s) for r in spark.sql(sql).collect()]
    spark.conf.set("spark.tpu.maxDeviceBatchBytes", 100_000)
    spark.conf.set("spark.tpu.chunkRows", 65_536)
    try:
        metrics.reset()
        got = [(r.n, r.m, r.s) for r in spark.sql(sql).collect()]
        evs = _chunk_events("chunked_agg")
        assert evs and evs[-1]["key_filters"] == 0
        assert evs[-1]["rows_kept"] == evs[-1]["rows_in"]
    finally:
        spark.conf.unset("spark.tpu.maxDeviceBatchBytes")
        spark.conf.unset("spark.tpu.chunkRows")
    assert got == want


def test_grace_hash_join_aggregation(spark, join_parquet):
    """Tier 3: both sides over budget -> hash-partitioned host buckets,
    per-bucket device joins."""
    from spark_tpu import metrics

    sql = ("select sum(v * w) as s, count(*) as n "
           "from oc_fact join oc_dim on k = dk")
    want = [(r.s, r.n) for r in spark.sql(sql).collect()]
    spark.conf.set("spark.tpu.maxDeviceBatchBytes", 1024)  # both "big"
    spark.conf.set("spark.tpu.chunkRows", 32_768)
    # pin the static grace tier (the hybrid join's fallback rung)
    spark.conf.set("spark.tpu.join.hybrid.enabled", False)
    try:
        metrics.reset()
        got = [(r.s, r.n) for r in spark.sql(sql).collect()]
        evs = _chunk_events("grace_hash_agg")
        assert evs and evs[-1]["partitions"] >= 2
    finally:
        spark.conf.unset("spark.tpu.maxDeviceBatchBytes")
        spark.conf.unset("spark.tpu.chunkRows")
        spark.conf.unset("spark.tpu.join.hybrid.enabled")
    assert got == want


def test_grace_hash_left_join(spark, join_parquet):
    from spark_tpu import metrics

    sql = ("select count(*) as n, count(w) as m "
           "from oc_fact left join oc_dim on k = dk")
    want = [(r.n, r.m) for r in spark.sql(sql).collect()]
    spark.conf.set("spark.tpu.maxDeviceBatchBytes", 1024)
    spark.conf.set("spark.tpu.join.hybrid.enabled", False)
    try:
        metrics.reset()
        got = [(r.n, r.m) for r in spark.sql(sql).collect()]
        assert _chunk_events("grace_hash_agg")
    finally:
        spark.conf.unset("spark.tpu.maxDeviceBatchBytes")
        spark.conf.unset("spark.tpu.join.hybrid.enabled")
    assert got == want


def test_chunked_topk(spark, join_parquet):
    """Streamed top-k: Limit(Sort(big scan)) merges a running device
    top-k instead of materializing the scan."""
    from spark_tpu import metrics

    sql = ("select k, v from oc_fact where v >= 10 "
           "order by v desc, k asc limit 7")
    want = [(r.k, r.v) for r in spark.sql(sql).collect()]
    spark.conf.set("spark.tpu.maxDeviceBatchBytes", 100_000)
    spark.conf.set("spark.tpu.chunkRows", 32_768)
    try:
        metrics.reset()
        got = [(r.k, r.v) for r in spark.sql(sql).collect()]
        evs = _chunk_events("chunked_topk")
        assert evs and evs[-1]["chunks"] >= 2
    finally:
        spark.conf.unset("spark.tpu.maxDeviceBatchBytes")
        spark.conf.unset("spark.tpu.chunkRows")
    assert got == want


# -- async chunk pipeline --------------------------------------------------


def _with_oc_conf(spark, depth, **extra):
    spark.conf.set("spark.tpu.maxDeviceBatchBytes", extra.pop(
        "maxDeviceBatchBytes", 100_000))
    spark.conf.set("spark.tpu.chunkRows", extra.pop("chunkRows", 16_384))
    spark.conf.set("spark.tpu.pipelineDepth", depth)
    for k, v in extra.items():
        spark.conf.set(k, v)


def _unset_oc_conf(spark, *extra):
    for k in ("spark.tpu.maxDeviceBatchBytes", "spark.tpu.chunkRows",
              "spark.tpu.pipelineDepth") + extra:
        spark.conf.unset(k)


def test_pipeline_depth_sweep_chunked_agg(spark, join_parquet):
    """Pipelined execution is byte-identical to serial: one producer
    thread feeds a FIFO queue, so the device merge order (and thus
    float accumulation order) never changes with depth."""
    from spark_tpu import metrics

    sql = ("select k % 7 as g, sum(v * w) as s, count(*) as n "
           "from oc_fact join oc_dim on k = dk group by k % 7 "
           "order by g")
    # integer sums: the resident path is comparable EXACTLY too
    want = [(r.g, r.s, r.n) for r in spark.sql(sql).collect()]
    by_depth = {}
    for depth in (0, 1, 2):
        _with_oc_conf(spark, depth)
        try:
            metrics.reset()
            by_depth[depth] = [(r.g, r.s, r.n)
                               for r in spark.sql(sql).collect()]
            evs = _chunk_events("chunked_agg")
            assert evs and evs[-1]["chunks"] >= 2
            assert evs[-1]["pipeline_depth"] == depth
        finally:
            _unset_oc_conf(spark)
    assert by_depth[0] == want  # chunked == resident (integer sums)
    # EXACT equality across depths — not approx
    assert by_depth[1] == by_depth[0]
    assert by_depth[2] == by_depth[0]


def test_pipeline_depth_sweep_grace_hash(spark, join_parquet):
    """Grace-hash joins pipeline the per-bucket passes; bucket order is
    unchanged, so results are exactly identical at every depth."""
    from spark_tpu import metrics

    sql = ("select sum(v * w) as s, count(*) as n "
           "from oc_fact join oc_dim on k = dk")
    want = [(r.s, r.n) for r in spark.sql(sql).collect()]
    by_depth = {}
    for depth in (0, 1, 2):
        _with_oc_conf(spark, depth, maxDeviceBatchBytes=1024,
                      chunkRows=32_768,
                      **{"spark.tpu.join.hybrid.enabled": False})
        try:
            metrics.reset()
            by_depth[depth] = [(r.s, r.n)
                               for r in spark.sql(sql).collect()]
            evs = _chunk_events("grace_hash_agg")
            assert evs and evs[-1]["partitions"] >= 2
            assert evs[-1]["pipeline_depth"] == depth
        finally:
            _unset_oc_conf(spark, "spark.tpu.join.hybrid.enabled")
    assert by_depth[0] == want  # chunked == resident (integer sums)
    assert by_depth[1] == by_depth[0]
    assert by_depth[2] == by_depth[0]


def test_pipeline_depth_sweep_topk(spark, join_parquet):
    from spark_tpu import metrics

    sql = ("select k, v from oc_fact where v >= 10 "
           "order by v desc, k asc limit 9")
    by_depth = {}
    for depth in (0, 2):
        _with_oc_conf(spark, depth, chunkRows=32_768)
        try:
            metrics.reset()
            by_depth[depth] = [(r.k, r.v)
                               for r in spark.sql(sql).collect()]
            assert _chunk_events("chunked_topk")
        finally:
            _unset_oc_conf(spark)
    assert by_depth[2] == by_depth[0]


def test_pipeline_byte_budget_bounds_inflight(spark, big_parquet):
    """prefetchBytesMax caps prepared-but-unconsumed chunks: a 1-byte
    budget admits exactly one chunk at a time (and must not deadlock)."""
    from spark_tpu import metrics

    path, _ = big_parquet
    df = spark.read.parquet(path)
    agg = df.groupBy("k").agg(F.sum("v").alias("s"),
                              F.count("v").alias("n"))
    want = {r.k: (r.s, r.n) for r in agg.collect()}
    _with_oc_conf(spark, 2, maxDeviceBatchBytes=1024,
                  **{"spark.tpu.prefetchBytesMax": 1})
    try:
        metrics.reset()
        got = {r.k: (r.s, r.n) for r in agg.collect()}
        evs = _chunk_events("chunked_agg")
        assert evs and evs[-1]["chunks"] >= 6
        assert evs[-1]["max_inflight_chunks"] == 1
    finally:
        _unset_oc_conf(spark, "spark.tpu.prefetchBytesMax")
    # resident vs chunked differ in float accumulation ORDER (that's
    # inherent to chunking, not the pipeline): approx for sums,
    # exact for counts
    assert set(got) == set(want)
    for k in want:
        assert got[k][1] == want[k][1]
        assert got[k][0] == pytest.approx(want[k][0], rel=1e-9)


def test_pipeline_producer_error_relayed_under_backpressure():
    """A producer error while the queue is FULL (the steady state of an
    active pipeline) must still be relayed to the consumer — dropping
    it would leave the consumer blocked on get() forever and lose the
    original exception."""
    import threading
    import time

    from spark_tpu.metrics import PipelineStats
    from spark_tpu.physical.pipeline import ChunkPipeline

    def source():
        yield from (1, 2, 3)
        raise ValueError("decode failed")

    pipe = ChunkPipeline(source(), lambda x: x, depth=2,
                         byte_budget=1 << 30, stats=PipelineStats())
    got, err = [], []

    def consume():
        try:
            for item in pipe:
                got.append(item)
                time.sleep(0.2)  # slow consumer -> queue stays full
        except ValueError as e:
            err.append(e)

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    t.join(timeout=15)
    assert not t.is_alive(), "consumer hung: producer error was dropped"
    assert got == [1, 2, 3]
    assert err and "decode failed" in str(err[0])


def test_pipeline_overlap_recorded(spark, big_parquet):
    """With depth >= 1 on a multi-chunk aggregation, the producer's
    decode/transfer genuinely overlaps device compute — the concurrency
    clock (wall time with both a producer and a consumer stage active)
    must be non-zero."""
    from spark_tpu import metrics

    path, _ = big_parquet
    agg = (spark.read.parquet(path).groupBy("k")
           .agg(F.sum("v").alias("s"), F.avg("v").alias("a"),
                F.max("w").alias("hi")))
    _with_oc_conf(spark, 2, maxDeviceBatchBytes=1024, chunkRows=8_192)
    try:
        metrics.reset()
        agg.collect()
        evs = _chunk_events("chunked_agg")
        assert evs and evs[-1]["chunks"] >= 10
        ev = evs[-1]
        assert ev["pipeline_depth"] == 2
        assert ev["overlap_ms"] > 0.0
        assert ev["overlap_ratio"] > 0.0
        assert ev["wall_ms"] >= ev["overlap_ms"]
        for stage in ("decode_ms", "transfer_ms", "compute_ms"):
            assert ev[stage] >= 0.0
    finally:
        _unset_oc_conf(spark)


def test_skewed_join_split_non_broadcastable(spark):
    """Build side over SKEW_MAX_BROADCAST_BYTES: the join SPLITS around
    the hot key (hot probe rows stay row-sliced against a broadcast of
    only the hot build rows) instead of inflating every device's pair
    capacity (reference: OptimizeSkewedJoin.scala:37)."""
    from spark_tpu import conf as _conf
    from spark_tpu import metrics
    from spark_tpu.expr import expressions as E
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh
    from spark_tpu.plan import logical as L

    n = 8000
    fact = spark.createDataFrame(
        [{"k": (1 if i % 10 else i % 400), "v": i} for i in range(n)])
    dim = spark.createDataFrame(
        [{"k": i, "w": i * 2} for i in range(400)])
    plan = L.Aggregate(
        (), (E.Alias(E.Count(None), "n"), E.Alias(E.Sum(E.Col("w")), "s")),
        L.Join(fact._plan, dim._plan, "inner",
               (E.Col("k"),), (E.Col("k"),)))
    conf = _conf.RuntimeConf()
    conf.set("spark.tpu.skewJoin.maxBroadcastBytes", 1)  # no demotion
    conf.set("spark.tpu.skewJoin.minPairs", 1000)
    ex = MeshExecutor(make_mesh(8), broadcast_threshold=1, conf=conf)
    metrics.reset()
    r = ex.execute_logical(plan).to_pylist()[0]
    evs = [e for e in metrics.recent(500) if e["kind"] == "skew_join_split"]
    assert evs, "split path did not engage"
    assert evs[-1]["hot_keys"] >= 1
    assert r["n"] == n
    want_s = sum((1 if i % 10 else i % 400) * 2 for i in range(n))
    assert r["s"] == want_s


def test_skewed_left_join_split_parity(spark):
    """Split preserves left-outer semantics: unmatched and NULL probe
    keys survive through the REST branch."""
    from spark_tpu import conf as _conf
    from spark_tpu import metrics
    from spark_tpu.expr import expressions as E
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh
    from spark_tpu.plan import logical as L

    import pyarrow as pa
    import numpy as np

    n = 6000
    ks = np.array([(7 if i % 5 else i % 900) for i in range(n)],
                  dtype=np.int64)
    fact = pa.table({
        "k": pa.array(ks, pa.int64()),
        "v": pa.array(np.arange(n), pa.int64()),
    })
    # every 97th key is NULL
    kmask = np.arange(n) % 97 == 0
    fact = fact.set_column(0, "k", pa.array(
        np.where(kmask, 0, ks), pa.int64(), mask=kmask))
    dim = spark.createDataFrame(
        [{"k": i, "w": i * 3} for i in range(0, 500)])  # 500..899 unmatched
    fdf = spark.createDataFrame(fact)
    plan = L.Aggregate(
        (), (E.Alias(E.Count(None), "n"),
             E.Alias(E.Count(E.Col("w")), "m"),
             E.Alias(E.Sum(E.Col("w")), "s")),
        L.Join(fdf._plan, dim._plan, "left",
               (E.Col("k"),), (E.Col("k"),)))
    conf = _conf.RuntimeConf()
    conf.set("spark.tpu.skewJoin.maxBroadcastBytes", 1)
    conf.set("spark.tpu.skewJoin.minPairs", 1000)
    ex = MeshExecutor(make_mesh(8), broadcast_threshold=1, conf=conf)
    metrics.reset()
    r = ex.execute_logical(plan).to_pylist()[0]
    assert [e for e in metrics.recent(500)
            if e["kind"] == "skew_join_split"]
    # oracle
    want_n = want_m = 0
    want_s = 0
    for i in range(n):
        if i % 97 == 0:
            want_n += 1  # null key: left row kept, no match
            continue
        k = 7 if i % 5 else i % 900
        if k < 500:
            want_n += 1
            want_m += 1
            want_s += k * 3
        else:
            want_n += 1
    assert (r["n"], r["m"], r["s"]) == (want_n, want_m, want_s)


# -- grant-driven hybrid hash join -----------------------------------------


@pytest.mark.parametrize("dist", ["uniform", "skewed"])
def test_hybrid_budget_ladder_byte_identity(spark, tmp_path, dist):
    """The hybrid join is byte-identical to the resident plan at EVERY
    grant level — unconstrained (all partitions stay resident),
    constrained (some spill) and near-floor (almost everything spills)
    — for uniform and 90%-one-key skewed key distributions."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_tpu import metrics

    rng = np.random.default_rng(41)
    n = 60_000
    if dist == "uniform":
        ks = rng.integers(0, 1000, n)
    else:  # 90% of rows share key 70 (a dim-matched key)
        ks = np.where(rng.random(n) < 0.9, 70,
                      rng.integers(0, 1000, n))
    fact = pa.table({"k": pa.array(ks.astype(np.int64), pa.int64()),
                     "v": pa.array(rng.integers(0, 100, n), pa.int64())})
    dim = pa.table({"dk": pa.array(np.arange(100) * 10, pa.int64()),
                    "w": pa.array(np.arange(100) * 2, pa.int64())})
    fp = str(tmp_path / f"hyf_{dist}.parquet")
    dp = str(tmp_path / f"hyd_{dist}.parquet")
    pq.write_table(fact, fp)
    pq.write_table(dim, dp)
    spark.read.parquet(fp).createOrReplaceTempView("hy_fact")
    spark.read.parquet(dp).createOrReplaceTempView("hy_dim")
    sql = ("select sum(v * w) as s, count(*) as n "
           "from hy_fact join hy_dim on k = dk")
    want = [(r.s, r.n) for r in spark.sql(sql).collect()]  # resident
    assert want[0][1] > 0
    spark.conf.set("spark.tpu.maxDeviceBatchBytes", 1024)
    spark.conf.set("spark.tpu.chunkRows", 32_768)
    spark.conf.set("spark.tpu.join.hybrid.partitionsMax", 16)
    try:
        for budget, expect_spill in ((2 << 30, False),
                                     (512 * 1024, True),
                                     (96 * 1024, True)):
            spark.conf.set("spark.tpu.scheduler.hbmBudgetBytes", budget)
            metrics.reset()
            metrics.reset_join()
            got = [(r.s, r.n) for r in spark.sql(sql).collect()]
            assert got == want, (dist, budget)  # EXACT integer sums
            evs = _chunk_events("hybrid_hash_agg")
            assert evs and evs[-1]["partitions"] >= 2
            js = metrics.join_stats()
            assert js["grants"] >= 1
            if expect_spill:
                assert evs[-1]["spilled_parts"] >= 1
                assert js["spill_writes"] >= 1
                assert js["spill_reads"] >= 1
                assert evs[-1]["granted_bytes"] <= budget
            else:
                assert evs[-1]["spilled_parts"] == 0
    finally:
        spark.conf.unset("spark.tpu.maxDeviceBatchBytes")
        spark.conf.unset("spark.tpu.chunkRows")
        spark.conf.unset("spark.tpu.join.hybrid.partitionsMax")
        spark.conf.unset("spark.tpu.scheduler.hbmBudgetBytes")


@pytest.mark.parametrize("devices", [1, 2, 8])
def test_hybrid_device_sweep_byte_identity(spark, tmp_path, devices):
    """find_chunkable routes to the hybrid join and the result matches
    a host-side oracle exactly on 1-, 2- and 8-device meshes (the
    per-bucket feeds ride whatever executor run_fn wraps)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_tpu import conf as _conf
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh
    from spark_tpu.physical.chunked import (_HybridHashJoinAgg,
                                            execute_chunked,
                                            find_chunkable)
    from spark_tpu.plan.optimizer import optimize

    rng = np.random.default_rng(43)
    n = 20_000
    fact = pa.table({
        "k": pa.array(rng.integers(0, 500, n), pa.int64()),
        "v": pa.array(rng.integers(0, 100, n), pa.int64()),
    })
    # 100 rows x 2 int64 cols = 1.6 KB: over the 1 KiB budget below,
    # so BOTH sides are "big" and the tier-3 hybrid join engages
    dim = pa.table({
        "dk": pa.array(np.arange(100) * 10, pa.int64()),
        "w": pa.array(np.arange(100) * 2, pa.int64()),
    })
    fp, dp = str(tmp_path / "dsf.parquet"), str(tmp_path / "dsd.parquet")
    pq.write_table(fact, fp)
    pq.write_table(dim, dp)
    spark.read.parquet(fp).createOrReplaceTempView("ds_fact")
    spark.read.parquet(dp).createOrReplaceTempView("ds_dim")
    wmap = {int(k): int(w) for k, w in
            zip(dim["dk"].to_pylist(), dim["w"].to_pylist())}
    hits = [int(v) * wmap[int(k)] for k, v in
            zip(fact["k"].to_pylist(), fact["v"].to_pylist())
            if int(k) in wmap]
    want = (sum(hits), len(hits))

    df = spark.sql("select sum(v * w) as s, count(*) as n "
                   "from ds_fact join ds_dim on k = dk")
    conf = _conf.RuntimeConf()
    conf.set("spark.tpu.maxDeviceBatchBytes", 1024)
    conf.set("spark.tpu.chunkRows", 16_384)
    conf.set("spark.tpu.join.hybrid.partitionsMax", 4)
    found = find_chunkable(optimize(df._plan), conf)
    assert isinstance(found, _HybridHashJoinAgg)
    ex = MeshExecutor(make_mesh(devices))
    out = execute_chunked(found, conf, lambda p: ex.execute_logical(p))
    row = out.to_pylist()[0]
    assert (row["s"], row["n"]) == want


def test_hybrid_recursive_repartition_depth(spark, join_parquet):
    """Two coarse partitions over a 200k-row fact force the recursive
    repartition at least two levels deep; results stay exact."""
    from spark_tpu import metrics

    sql = ("select sum(v * w) as s, count(*) as n "
           "from oc_fact join oc_dim on k = dk")
    want = [(r.s, r.n) for r in spark.sql(sql).collect()]
    spark.conf.set("spark.tpu.maxDeviceBatchBytes", 1024)
    spark.conf.set("spark.tpu.chunkRows", 65_536)
    spark.conf.set("spark.tpu.join.hybrid.partitionsMax", 2)
    try:
        metrics.reset()
        metrics.reset_join()
        got = [(r.s, r.n) for r in spark.sql(sql).collect()]
        evs = _chunk_events("hybrid_hash_agg")
        assert evs and evs[-1]["depth"] >= 2
        assert metrics.join_stats()["recursive_repartitions"] >= 2
    finally:
        spark.conf.unset("spark.tpu.maxDeviceBatchBytes")
        spark.conf.unset("spark.tpu.chunkRows")
        spark.conf.unset("spark.tpu.join.hybrid.partitionsMax")
    assert got == want


@pytest.mark.parametrize("kind", ["transient", "hang", "corrupt", "oom"])
def test_hybrid_spill_fault_matrix(spark, join_parquet, kind):
    """join.spill x all four fault kinds, armed under a starved grant so
    the spill seams actually run: transient/hang retry in place,
    corrupt falls back one rung (grace recompute from source), oom
    surfaces to the degradation ladder — bytes identical on every
    surviving path."""
    from spark_tpu import metrics

    sql = ("select sum(v * w) as s, count(*) as n "
           "from oc_fact join oc_dim on k = dk")
    want = [(r.s, r.n) for r in spark.sql(sql).collect()]
    spark.conf.set("spark.tpu.maxDeviceBatchBytes", 1024)
    spark.conf.set("spark.tpu.chunkRows", 32_768)
    spark.conf.set("spark.tpu.join.hybrid.partitionsMax", 64)
    spark.conf.set("spark.tpu.scheduler.hbmBudgetBytes", 64 * 1024)
    spark.conf.set("spark.tpu.faultInjection.join.spill",
                   f"nth:1:{kind}")
    spark.conf.set("spark.tpu.faultInjection.hangSeconds", 0.05)
    try:
        metrics.reset()
        metrics.reset_join()
        metrics.reset_recovery()
        if kind == "oom":
            with pytest.raises(Exception) as ei:
                spark.sql(sql).collect()
            assert "RESOURCE_EXHAUSTED" in str(ei.value)
            assert metrics.recovery_stats()["ladder_exhausted"] >= 1
        else:
            got = [(r.s, r.n) for r in spark.sql(sql).collect()]
            js = metrics.join_stats()
            if kind in ("transient", "hang"):
                assert js["spill_retries"] >= 1
                assert js["fallbacks"] == 0
                assert _chunk_events("hybrid_hash_agg")
            else:  # corrupt: not retryable -> grace recompute
                assert js["fallbacks"] >= 1
                assert _chunk_events("grace_hash_agg")
            assert got == want
    finally:
        spark.conf.unset("spark.tpu.maxDeviceBatchBytes")
        spark.conf.unset("spark.tpu.chunkRows")
        spark.conf.unset("spark.tpu.join.hybrid.partitionsMax")
        spark.conf.unset("spark.tpu.scheduler.hbmBudgetBytes")
        spark.conf.unset("spark.tpu.faultInjection.join.spill")
        spark.conf.unset("spark.tpu.faultInjection.hangSeconds")


def test_hybrid_concurrent_tenant_budget_invariant(spark, join_parquet):
    """execution grants + storage never exceed the unified budget while
    the hybrid join runs against a concurrent tenant hammering
    acquire/release on the same manager."""
    import threading
    import time

    from spark_tpu import metrics

    sql = ("select sum(v * w) as s, count(*) as n "
           "from oc_fact join oc_dim on k = dk")
    want = [(r.s, r.n) for r in spark.sql(sql).collect()]
    mgr = spark.memory_manager
    # drop batches cached by earlier tests: shrinking the budget below
    # ALREADY-resident storage would manufacture a violation the
    # manager never admitted (eviction only runs at admission time)
    spark.memory_store.clear()
    spark.conf.set("spark.tpu.maxDeviceBatchBytes", 1024)
    spark.conf.set("spark.tpu.chunkRows", 32_768)
    spark.conf.set("spark.tpu.join.hybrid.partitionsMax", 16)
    spark.conf.set("spark.tpu.scheduler.hbmBudgetBytes", 192 * 1024)
    stop = threading.Event()
    violations = []

    def check():
        snap = mgr.snapshot()
        if snap["in_use_bytes"] + snap["storage_bytes"] \
                > snap["budget_bytes"]:
            violations.append(snap)

    def tenant():
        while not stop.is_set():
            c = mgr.acquire_execution(32 * 1024)
            check()
            time.sleep(0.001)
            mgr.release_execution(c)

    def sampler():
        while not stop.is_set():
            check()
            time.sleep(0.0005)

    threads = [threading.Thread(target=tenant, daemon=True),
               threading.Thread(target=sampler, daemon=True)]
    try:
        metrics.reset()
        metrics.reset_join()
        for t in threads:
            t.start()
        got = [(r.s, r.n) for r in spark.sql(sql).collect()]
        assert _chunk_events("hybrid_hash_agg")
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        spark.conf.unset("spark.tpu.maxDeviceBatchBytes")
        spark.conf.unset("spark.tpu.chunkRows")
        spark.conf.unset("spark.tpu.join.hybrid.partitionsMax")
        spark.conf.unset("spark.tpu.scheduler.hbmBudgetBytes")
    assert not violations, violations[:3]
    assert got == want


def test_hybrid_zero_replans_where_ladder_replanned(spark, join_parquet):
    """The acceptance bar: under a starved grant the hybrid join
    completes as ONE planned pass (recovery replans == 0 even though
    spills prove memory really was short); the old reactive path pays
    >= 1 ladder replan for the same kind of pressure."""
    from spark_tpu import metrics

    sql = ("select sum(v * w) as s, count(*) as n "
           "from oc_fact join oc_dim on k = dk")
    want = [(r.s, r.n) for r in spark.sql(sql).collect()]

    # NEW: planned single pass under a starved grant
    spark.conf.set("spark.tpu.maxDeviceBatchBytes", 1024)
    spark.conf.set("spark.tpu.chunkRows", 32_768)
    spark.conf.set("spark.tpu.join.hybrid.partitionsMax", 64)
    spark.conf.set("spark.tpu.scheduler.hbmBudgetBytes", 64 * 1024)
    try:
        metrics.reset()
        metrics.reset_join()
        metrics.reset_recovery()
        got = [(r.s, r.n) for r in spark.sql(sql).collect()]
        assert metrics.join_stats()["spill_writes"] >= 1
        assert metrics.recovery_stats()["replans"] == 0
    finally:
        spark.conf.unset("spark.tpu.maxDeviceBatchBytes")
        spark.conf.unset("spark.tpu.chunkRows")
        spark.conf.unset("spark.tpu.join.hybrid.partitionsMax")
        spark.conf.unset("spark.tpu.scheduler.hbmBudgetBytes")
    assert got == want

    # OLD: resident execution dies with OOM -> reactive ladder replans
    spark.conf.set("spark.tpu.join.hybrid.enabled", False)
    spark.conf.set("spark.tpu.faultInjection.execute.device",
                   "nth:1:oom")
    try:
        metrics.reset_recovery()
        got2 = [(r.s, r.n) for r in spark.sql(sql).collect()]
        assert metrics.recovery_stats()["replans"] >= 1
    finally:
        spark.conf.unset("spark.tpu.join.hybrid.enabled")
        spark.conf.unset("spark.tpu.faultInjection.execute.device")
    assert got2 == want

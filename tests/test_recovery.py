"""Fault tolerance (spark_tpu/recovery.py; reference:
DAGScheduler.scala:1762 stage resubmission, HeartbeatReceiver.scala:67,
ReliableCheckpointRDD)."""

import time

import pytest

from spark_tpu import recovery


def test_transient_classification():
    assert recovery.is_transient(RuntimeError("DEADLINE_EXCEEDED: x"))
    assert recovery.is_transient(OSError("Connection reset by peer"))
    assert not recovery.is_transient(ValueError("column not found: x"))
    assert not recovery.is_transient(RuntimeError("RESOURCE_EXHAUSTED"))


def test_transient_classification_by_type():
    # transient by exception TYPE even with an unhelpful message
    assert recovery.is_transient(ConnectionResetError(""))
    assert recovery.is_transient(BrokenPipeError("x"))
    assert recovery.is_transient(TimeoutError(""))
    assert not recovery.is_transient(MemoryError())


def test_transient_classification_follows_cause_chain():
    # a wrapped timeout is still transient…
    try:
        try:
            raise TimeoutError("")
        except TimeoutError as inner:
            raise RuntimeError("stage 3 failed") from inner
    except RuntimeError as wrapped:
        assert recovery.is_transient(wrapped)
        assert not recovery.is_oom(wrapped)
    # …but OOM anywhere in the chain wins: never transient
    try:
        try:
            raise MemoryError()
        except MemoryError as inner:
            raise TimeoutError("gave up waiting") from inner
    except TimeoutError as wrapped:
        assert recovery.is_oom(wrapped)
        assert not recovery.is_transient(wrapped)


def test_transient_classification_xla_status_prefix():
    # jaxlib's XlaRuntimeError is matched by type NAME + status prefix
    # (jaxlib need not be importable by the classifier)
    XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
    assert recovery.is_transient(XlaRuntimeError("ABORTED: collective"))
    assert recovery.is_transient(XlaRuntimeError("INTERNAL: dma stall"))
    assert not recovery.is_transient(
        XlaRuntimeError("INVALID_ARGUMENT: shape mismatch"))
    assert not recovery.is_transient(
        XlaRuntimeError("RESOURCE_EXHAUSTED: out of memory on TPU_0"))
    assert recovery.is_oom(
        XlaRuntimeError("RESOURCE_EXHAUSTED: out of memory on TPU_0"))


def test_injected_fault_classification():
    from spark_tpu import faults

    assert recovery.is_transient(
        faults.InjectedTransientError("p", "UNAVAILABLE: x"))
    assert recovery.is_transient(
        faults.InjectedDeadlineError("p", "DEADLINE_EXCEEDED: x"))
    assert recovery.is_oom(faults.InjectedOOMError("p", "boom"))
    assert not recovery.is_transient(faults.InjectedOOMError("p", "boom"))
    # corrupt: neither transient nor OOM — must surface unretried
    corrupt = faults.InjectedCorruptionError("p", "DATA_LOSS: x")
    assert not recovery.is_transient(corrupt)
    assert not recovery.is_oom(corrupt)


def test_stage_retry_recovers_from_transient():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise RuntimeError("UNAVAILABLE: host dropped from collective")
        return 42

    assert recovery.run_stage_with_recovery(flaky) == 42
    assert calls["n"] == 3


def test_stage_retry_does_not_mask_bugs():
    calls = {"n": 0}

    def buggy():
        calls["n"] += 1
        raise ValueError("analysis error")

    with pytest.raises(ValueError):
        recovery.run_stage_with_recovery(buggy)
    assert calls["n"] == 1  # no retry for non-transient errors


def test_stage_retry_budget_exhausted():
    def always():
        raise RuntimeError("ABORTED: collective")

    with pytest.raises(RuntimeError, match="consecutive attempts"):
        recovery.run_stage_with_recovery(always)


def test_query_survives_transient_executor_failure(spark, monkeypatch):
    """End-to-end: a query whose first execution dies with a transient
    error completes on retry via lineage recompute."""
    from spark_tpu.physical import planner

    real = planner.execute_logical
    state = {"fails": 1}

    def flaky(plan, optimize=True):
        if state["fails"] > 0:
            state["fails"] -= 1
            raise RuntimeError("UNAVAILABLE: TPU slice has failed")
        return real(plan, optimize)

    monkeypatch.setattr(planner, "execute_logical", flaky)
    df = spark.range(100).filter("id % 2 = 0")
    assert df.count() == 50
    assert state["fails"] == 0


def test_heartbeat_monitor():
    mon = recovery.HeartbeatMonitor(interval_s=0.05).start()
    try:
        assert mon.healthy()
        time.sleep(0.2)
        assert mon.healthy()
        st = mon.status()
        assert st["last_ok"] is not None and st["last_error"] is None
    finally:
        mon.stop()


def test_heartbeat_detects_failure(monkeypatch):
    mon = recovery.HeartbeatMonitor(interval_s=0.05)
    mon.start()
    try:
        assert mon.healthy()
        monkeypatch.setattr(
            mon, "_probe",
            lambda: (_ for _ in ()).throw(RuntimeError("device gone")))
        time.sleep(0.25)
        assert not mon.healthy()
        assert "device gone" in mon.status()["last_error"]
    finally:
        mon.stop()


def test_dataframe_checkpoint_durable(spark, tmp_path):
    spark.conf.set("spark.checkpoint.dir", str(tmp_path))
    ck = spark.range(50).filter("id >= 10").checkpoint()
    # lineage truncated: the plan is a scan over files, not the range
    from spark_tpu.plan import logical as L

    assert isinstance(ck._plan, L.UnresolvedScan) or not L.collect_nodes(
        ck._plan, L.Range)
    assert ck.count() == 40
    assert sorted(r["id"] for r in ck.collect())[:3] == [10, 11, 12]


def test_dataframe_checkpoint_requires_dir(spark):
    spark.conf.set("spark.checkpoint.dir", "")
    with pytest.raises(RuntimeError, match="spark.checkpoint.dir"):
        spark.range(5).checkpoint()
    # localCheckpoint works without a directory
    assert spark.range(5).localCheckpoint().count() == 5


def test_checkpoint_paths_unique(spark, tmp_path):
    """Repeated checkpoints never collide: each lands in its own
    directory (counter under a lock + a uuid component, so even a
    fresh process re-using the directory cannot overwrite)."""
    import os

    spark.conf.set("spark.checkpoint.dir", str(tmp_path))
    for _ in range(3):
        spark.range(10).checkpoint()
    dirs = [d for d in os.listdir(tmp_path) if d.startswith("ckpt-")]
    assert len(dirs) == 3 and len(set(dirs)) == 3
    pid = str(os.getpid())
    assert all(pid in d for d in dirs)

"""pandas-on-spark subset (reference: python/pyspark/pandas/)."""

import pandas as pd
import pytest

import spark_tpu.pandas as ps


@pytest.fixture(scope="module")
def pdf(spark):
    data = pd.DataFrame({
        "k": ["a", "b", "a", "c", "b", "a"],
        "x": [1, 2, 3, 4, 5, 6],
        "y": [1.5, 2.5, 3.5, 4.5, 5.5, 6.5],
    })
    return data, ps.from_pandas(data)


def test_filter_and_select(pdf):
    data, f = pdf
    out = f[f.x > 3][["k", "x"]].to_pandas()
    want = data[data.x > 3][["k", "x"]].reset_index(drop=True)
    pd.testing.assert_frame_equal(
        out.sort_values("x").reset_index(drop=True),
        want.sort_values("x").reset_index(drop=True))


def test_column_arith_and_assign(pdf):
    _, f = pdf
    g = f.assign(z=f.x * 2 + f.y)
    out = g.to_pandas()
    assert (out.z == out.x * 2 + out.y).all()


def test_groupby_agg(pdf):
    data, f = pdf
    out = f.groupby("k").agg({"x": "sum", "y": "mean"}).to_pandas()
    want = data.groupby("k").agg(x=("x", "sum"), y=("y", "mean")) \
        .reset_index()
    pd.testing.assert_frame_equal(
        out.sort_values("k").reset_index(drop=True),
        want.sort_values("k").reset_index(drop=True))


def test_groupby_count_sum(pdf):
    data, f = pdf
    out = f.groupby("k").count().to_pandas()
    want = data.groupby("k").size()
    got = dict(zip(out.k, out["count"]))
    assert got == want.to_dict()


def test_merge(pdf):
    _, f = pdf
    dim = ps.from_pandas(pd.DataFrame(
        {"k": ["a", "b", "c"], "w": [10, 20, 30]}))
    out = f.merge(dim, on="k").to_pandas()
    assert len(out) == 6
    assert set(out.columns) >= {"k", "x", "y", "w"}
    assert (out[out.k == "a"].w == 10).all()


def test_reductions(pdf):
    data, f = pdf
    assert f.x.sum() == data.x.sum()
    assert f.y.mean() == pytest.approx(data.y.mean())
    assert f.x.max() == 6 and f.x.min() == 1
    assert f.k.nunique() == 3


def test_sort_head_len(pdf):
    data, f = pdf
    assert len(f) == 6
    top = f.sort_values("x", ascending=False).head(2)
    assert top.x.tolist() == [6, 5]


def test_describe(pdf):
    _, f = pdf
    d = f.describe()
    assert d.loc["count", "x"] == 6
    assert d.loc["max", "y"] == 6.5

"""pandas-on-spark subset (reference: python/pyspark/pandas/)."""

import pandas as pd
import pytest

import spark_tpu.pandas as ps


@pytest.fixture(scope="module")
def pdf(spark):
    data = pd.DataFrame({
        "k": ["a", "b", "a", "c", "b", "a"],
        "x": [1, 2, 3, 4, 5, 6],
        "y": [1.5, 2.5, 3.5, 4.5, 5.5, 6.5],
    })
    return data, ps.from_pandas(data)


def test_filter_and_select(pdf):
    data, f = pdf
    out = f[f.x > 3][["k", "x"]].to_pandas()
    want = data[data.x > 3][["k", "x"]].reset_index(drop=True)
    pd.testing.assert_frame_equal(
        out.sort_values("x").reset_index(drop=True),
        want.sort_values("x").reset_index(drop=True))


def test_column_arith_and_assign(pdf):
    _, f = pdf
    g = f.assign(z=f.x * 2 + f.y)
    out = g.to_pandas()
    assert (out.z == out.x * 2 + out.y).all()


def test_groupby_agg(pdf):
    data, f = pdf
    out = f.groupby("k").agg({"x": "sum", "y": "mean"}).to_pandas()
    want = data.groupby("k").agg(x=("x", "sum"), y=("y", "mean")) \
        .reset_index()
    pd.testing.assert_frame_equal(
        out.sort_values("k").reset_index(drop=True),
        want.sort_values("k").reset_index(drop=True))


def test_groupby_count_sum(pdf):
    data, f = pdf
    out = f.groupby("k").count().to_pandas()
    want = data.groupby("k").size()
    got = dict(zip(out.k, out["count"]))
    assert got == want.to_dict()


def test_merge(pdf):
    _, f = pdf
    dim = ps.from_pandas(pd.DataFrame(
        {"k": ["a", "b", "c"], "w": [10, 20, 30]}))
    out = f.merge(dim, on="k").to_pandas()
    assert len(out) == 6
    assert set(out.columns) >= {"k", "x", "y", "w"}
    assert (out[out.k == "a"].w == 10).all()


def test_reductions(pdf):
    data, f = pdf
    assert f.x.sum() == data.x.sum()
    assert f.y.mean() == pytest.approx(data.y.mean())
    assert f.x.max() == 6 and f.x.min() == 1
    assert f.k.nunique() == 3


def test_sort_head_len(pdf):
    data, f = pdf
    assert len(f) == 6
    top = f.sort_values("x", ascending=False).head(2)
    assert top.x.tolist() == [6, 5]


def test_describe(pdf):
    _, f = pdf
    d = f.describe()
    assert d.loc["count", "x"] == 6
    assert d.loc["max", "y"] == 6.5


def test_iloc_and_loc(spark):
    import spark_tpu.pandas as ps
    import pandas as pd

    psdf = ps.from_pandas(pd.DataFrame(
        {"a": range(10), "b": [i * 2 for i in range(10)]}))
    assert list(psdf.iloc[2:5].to_pandas()["a"]) == [2, 3, 4]
    assert list(psdf.iloc[:3].to_pandas()["a"]) == [0, 1, 2]
    row = psdf.iloc[4]
    assert (row["a"], row["b"]) == (4, 8)
    got = psdf.loc[psdf.a > 6, ["b"]].to_pandas()
    assert list(got["b"]) == [14, 16, 18] and list(got.columns) == ["b"]
    got2 = psdf.loc[:, ["a"]].to_pandas()
    assert list(got2.columns) == ["a"] and len(got2) == 10


def test_concat_aligns_columns(spark):
    import spark_tpu.pandas as ps
    import pandas as pd

    a = ps.from_pandas(pd.DataFrame({"x": [1, 2], "y": [10.0, 20.0]}))
    b = ps.from_pandas(pd.DataFrame({"x": [3], "z": [99.0]}))
    out = ps.concat([a, b]).to_pandas()
    assert list(out.columns) == ["x", "y", "z"]
    assert list(out["x"]) == [1, 2, 3]
    assert pd.isna(out["z"][0]) and out["z"][2] == 99.0
    assert pd.isna(out["y"][2])


def test_value_counts_and_ranking(spark):
    import spark_tpu.pandas as ps
    import pandas as pd

    psdf = ps.from_pandas(pd.DataFrame(
        {"k": ["a", "b", "a", "a", "c"], "v": [5, 3, 9, 1, 7]}))
    vc = psdf.value_counts("k").to_pandas()
    assert list(vc["k"])[0] == "a" and list(vc["count"])[0] == 3
    assert list(psdf.nlargest(2, "v").to_pandas()["v"]) == [9, 7]
    assert list(psdf.nsmallest(2, "v").to_pandas()["v"]) == [1, 3]


def test_fillna_dropna(spark):
    import spark_tpu.pandas as ps
    import pandas as pd
    import numpy as np

    psdf = ps.from_pandas(pd.DataFrame(
        {"a": [1.0, np.nan, 3.0], "b": [np.nan, 5.0, 6.0]}))
    filled = psdf.fillna(0.0).to_pandas()
    assert list(filled["a"]) == [1.0, 0.0, 3.0]
    dropped = psdf.dropna().to_pandas()
    assert len(dropped) == 1 and dropped["a"].iloc[0] == 3.0

"""MapType: decomposed '#keys'/'#vals' component pair (types.MapType;
reference: types/MapType.scala, ArrayBasedMapData.scala,
complexTypeCreator.scala CreateMap, complexTypeExtractors.scala
GetMapValue)."""

import pyarrow as pa
import pytest

from spark_tpu.api import functions as F


@pytest.fixture()
def mdf(spark):
    tbl = pa.table({
        "m": pa.array([{"a": 1, "b": 2}, {"c": 3}, None, {}],
                      pa.map_(pa.string(), pa.int64())),
        "k": pa.array(["a", "c", "a", "z"]),
        "x": pa.array([10, 20, 30, 40], pa.int64()),
    })
    df = spark.createDataFrame(tbl)
    df.createOrReplaceTempView("mt")
    return df


def test_ingest_and_roundtrip(spark, mdf):
    rows = spark.sql("select m, x from mt").collect()
    assert rows[0].m == {"a": 1, "b": 2}
    assert rows[1].m == {"c": 3}
    assert rows[2].m is None
    assert rows[3].m == {}
    out = spark.sql("select m from mt").toArrow()
    assert out.column("m").to_pylist() == [
        [("a", 1), ("b", 2)], [("c", 3)], None, []]


def test_element_at_and_subscript(spark, mdf):
    rows = spark.sql(
        "select element_at(m, 'a') as a, m['b'] as b, "
        "element_at(m, k) as dyn, size(m) as s from mt").collect()
    assert [r.a for r in rows] == [1, None, None, None]
    assert [r.b for r in rows] == [2, None, None, None]
    assert [r.dyn for r in rows] == [1, 3, None, None]
    assert [r.s for r in rows] == [2, 1, None, 0]


def test_keys_values_contains(spark, mdf):
    rows = spark.sql(
        "select map_keys(m) as mk, map_values(m) as mv, "
        "map_contains_key(m, 'c') as c from mt").collect()
    assert [r.mk for r in rows] == [["a", "b"], ["c"], None, []]
    assert [r.mv for r in rows] == [[1, 2], [3], None, []]
    assert [r.c for r in rows] == [False, True, None, False]


def test_create_map_and_from_arrays(spark, mdf):
    rows = spark.sql(
        "select map('x', x, 'y', x * 2) as built from mt").collect()
    assert rows[0].built == {"x": 10, "y": 20}
    assert rows[3].built == {"x": 40, "y": 80}
    r2 = spark.sql("select map_from_arrays(array('u', 'v'), "
                   "array(7, 8)) as mfa from mt limit 1").collect()
    assert r2[0].mfa == {"u": 7, "v": 8}


def test_create_map_api_and_write(spark, mdf, tmp_path):
    df = mdf.select(F.create_map(F.lit("k"), F.col("x")).alias("m2"),
                    F.col("x"))
    assert [r.m2 for r in df.collect()] == [
        {"k": 10}, {"k": 20}, {"k": 30}, {"k": 40}]
    # parquet write of a map column goes through the arrow pair rebuild
    import pyarrow.parquet as pq

    p = str(tmp_path / "maps.parquet")
    df.write.parquet(p)
    back = pq.read_table(p)
    assert back.column("m2").to_pylist()[0] == [("k", 10)]


def test_subscript_zero_based_array(spark, mdf):
    rows = spark.sql(
        "select array(5, 6, 7)[0] as a0, array(5, 6, 7)[2] as a2, "
        "array(5, 6, 7)[3] as oob from mt limit 1").collect()
    assert (rows[0].a0, rows[0].a2, rows[0].oob) == (5, 7, None)


def test_map_handle_alias_and_star(spark, mdf):
    rows = spark.sql("select m as q, x from mt where x = 10").collect()
    assert rows[0].q == {"a": 1, "b": 2}
    rows2 = spark.sql("select * from mt where x = 20").collect()
    assert rows2[0].m == {"c": 3} and rows2[0].k == "c"


def test_int_key_map(spark):
    tbl = pa.table({"m": pa.array([{1: 10.5, 2: 20.5}, {3: 30.5}],
                                  pa.map_(pa.int64(), pa.float64()))})
    spark.createDataFrame(tbl).createOrReplaceTempView("imt")
    rows = spark.sql("select m[2] as v, element_at(m, 3) as w "
                     "from imt").collect()
    assert [r.v for r in rows] == [20.5, None]
    assert [r.w for r in rows] == [None, 30.5]

"""df.na / df.stat / describe (spark_tpu/api/na_stat.py; reference:
DataFrameNaFunctions.scala, DataFrameStatFunctions.scala)."""

import math

import pyarrow as pa
import pytest


@pytest.fixture
def df(spark):
    return spark.createDataFrame(pa.table({
        "a": pa.array([1, None, 3, None, 5], pa.int64()),
        "b": pa.array([10.0, 20.0, None, None, 50.0]),
        "s": pa.array(["x", None, "y", "x", None]),
    }))


def test_dropna_any_all_thresh(df):
    assert df.na.drop().count() == 1        # only row 0 fully non-null
    assert df.na.drop("all").count() == 5   # no row is ALL-null
    assert df.na.drop("all", subset=["a", "b"]).count() == 4  # row 3 is
    assert df.dropna(subset=["a"]).count() == 3
    assert df.na.drop(thresh=2).count() == 3
    assert df.na.drop(thresh=1).count() == 5


def test_fillna(df):
    out = df.fillna(0, subset=["a"]).collect()
    assert [r["a"] for r in out] == [1, 0, 3, 0, 5]
    out2 = df.fillna({"a": -1, "b": 9.5}).collect()
    assert [r["a"] for r in out2] == [1, -1, 3, -1, 5]
    assert [r["b"] for r in out2] == [10.0, 20.0, 9.5, 9.5, 50.0]
    # string fill leaves numerics alone
    out3 = df.fillna("zz").collect()
    assert [r["s"] for r in out3] == ["x", "zz", "y", "x", "zz"]
    assert [r["a"] for r in out3] == [1, None, 3, None, 5]


def test_replace(df):
    out = df.replace(1, 100, subset=["a"]).collect()
    assert [r["a"] for r in out] == [100, None, 3, None, 5]
    out2 = df.replace([10.0, 50.0], [11.0, 51.0]).collect()
    assert [r["b"] for r in out2] == [11.0, 20.0, None, None, 51.0]


def test_corr_cov(spark):
    xs = list(range(50))
    ys = [3.0 * x + 1.0 for x in xs]
    d = spark.createDataFrame(pa.table({
        "x": pa.array([float(x) for x in xs]),
        "y": pa.array(ys)}))
    assert abs(d.corr("x", "y") - 1.0) < 1e-9
    import numpy as np

    want_cov = float(np.cov(xs, ys)[0][1])
    assert abs(d.cov("x", "y") - want_cov) < 1e-6


def test_approx_quantile(spark):
    d = spark.createDataFrame(pa.table({
        "v": pa.array([float(i) for i in range(100)])}))
    q = d.approxQuantile("v", [0.0, 0.5, 0.99])
    assert q[0] == 0.0 and 49.0 <= q[1] <= 51.0 and q[2] >= 98.0


def test_crosstab_freqitems(spark):
    d = spark.createDataFrame(pa.table({
        "k": pa.array(["a", "a", "b", "b", "b"]),
        "v": pa.array([1, 2, 1, 1, 2], pa.int64())}))
    ct = {r["k_v"]: (r["1"], r["2"]) for r in d.crosstab("k", "v").collect()}
    assert ct == {"a": (1, 1), "b": (2, 1)}
    import json

    fi = json.loads(d.freqItems(["k"], support=0.5)
                    .collect()[0]["k_freqItems"])
    assert fi == ["b"]


def test_sample_by(spark):
    d = spark.range(1000).withColumn(
        "g", __import__("spark_tpu.expr.expressions",
                        fromlist=["Col"]).Col("id") % 2)
    out = d.sampleBy("g", {0: 0.0, 1: 1.0}, seed=1)
    rows = out.collect()
    assert all(r["g"] == 1 for r in rows)
    assert 400 <= len(rows) <= 500


def test_describe(spark):
    d = spark.createDataFrame(pa.table({
        "v": pa.array([1.0, 2.0, 3.0, 4.0])}))
    rows = {r["summary"]: r["v"] for r in d.describe().collect()}
    assert rows["count"] == "4"
    assert float(rows["mean"]) == 2.5
    assert abs(float(rows["stddev"]) - 1.2909944487358056) < 1e-9
    assert float(rows["min"]) == 1.0 and float(rows["max"]) == 4.0


def test_corr_cov_pairwise_null_deletion(spark):
    """corr/cov must use pairwise deletion (rows where BOTH columns are
    non-null), not per-column null skipping (reference:
    StatFunctions.pearsonCorrelation / calculateCov co-moments)."""
    d = spark.createDataFrame(pa.table({
        "x": pa.array([1.0, 2.0, 3.0, None, 100.0]),
        "y": pa.array([2.0, 4.0, 6.0, 50.0, None]),
    }))
    # surviving pairs: (1,2),(2,4),(3,6) — perfectly correlated
    assert abs(d.stat.corr("x", "y") - 1.0) < 1e-12
    assert abs(d.stat.cov("x", "y") - 2.0) < 1e-12  # cov([1,2,3],[2,4,6])

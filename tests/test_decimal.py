"""Exact decimal arithmetic (reference: sql/catalyst/.../types/
Decimal.scala + expressions/decimalExpressions.scala + DecimalPrecision
rules). The engine represents Decimal(p<=18, s) as scaled int64 on
device; money math must be EXACT — verified here with EQUALITY (no
tolerance) against integer arithmetic done independently in numpy/
python-decimal over the same inputs."""

import decimal

import numpy as np
import pyarrow as pa
import pytest

from spark_tpu.api import functions as F

D = decimal.Decimal


@pytest.fixture(scope="module")
def money_df(spark):
    rng = np.random.default_rng(3)
    n = 20_000
    cents = rng.integers(-10_000_00, 100_000_00, n)
    disc = rng.integers(0, 11, n)  # 0.00 .. 0.10
    qty = rng.integers(1, 51, n)

    def dec_col(unscaled, typ):
        buf = np.empty((len(unscaled), 2), dtype=np.int64)
        buf[:, 0] = unscaled
        buf[:, 1] = np.where(unscaled < 0, -1, 0)
        return pa.Array.from_buffers(
            typ, len(unscaled), [None, pa.py_buffer(buf.tobytes())])

    tbl = pa.table({
        "price": dec_col(cents, pa.decimal128(12, 2)),
        "disc": dec_col(disc, pa.decimal128(12, 2)),
        "qty": pa.array(qty, pa.int64()),
    })
    df = spark.createDataFrame(tbl)
    df.createOrReplaceTempView("money")
    return df, cents, disc, qty


def test_sum_exact_no_tolerance(money_df, spark):
    df, cents, disc, qty = money_df
    got = df.agg(F.sum(F.col("price")).alias("s")).collect()[0]["s"]
    want = D(int(cents.sum())).scaleb(-2)
    assert got == want  # EXACT equality, not approx
    assert isinstance(got, decimal.Decimal)


def test_q1_shape_exact(money_df, spark):
    """sum(price * (1 - disc)) — the TPC-H q1/q3/q5 revenue shape —
    exactly equals integer arithmetic at scale 4."""
    df, cents, disc, qty = money_df
    got = spark.sql(
        "select sum(price * (1 - disc)) as rev from money"
    ).collect()[0]["rev"]
    # integer oracle: price(s2) * (1-disc)(s2) -> unscaled at s4
    want_unscaled = int((cents * (100 - disc)).sum())
    assert got == D(want_unscaled).scaleb(-4)


def test_mul_scale_and_precision(spark):
    tbl = pa.table({"a": pa.array([D("1.25")], pa.decimal128(5, 2)),
                    "b": pa.array([D("0.5")], pa.decimal128(5, 1))})
    d = spark.createDataFrame(tbl)
    r = d.select((F.col("a") * F.col("b")).alias("v")).collect()[0]["v"]
    assert r == D("0.625")  # scale 3, exact


def test_add_aligns_scales(spark):
    tbl = pa.table({"a": pa.array([D("1.25")], pa.decimal128(5, 2)),
                    "b": pa.array([D("0.5")], pa.decimal128(5, 1))})
    d = spark.createDataFrame(tbl)
    r = d.select((F.col("a") + F.col("b")).alias("v")).collect()[0]["v"]
    assert r == D("1.75")
    r2 = d.select((F.col("a") - F.col("b")).alias("v")).collect()[0]["v"]
    assert r2 == D("0.75")


def test_div_rounds_half_up(spark):
    tbl = pa.table({"a": pa.array([D("1.00")], pa.decimal128(5, 2))})
    d = spark.createDataFrame(tbl)
    r = d.select((F.col("a") / F.lit(3)).alias("v")).collect()[0]["v"]
    # Spark rule gives (25, 22); the engine's 18-digit cap reduces the
    # scale to fit the integral part: (18, 15)
    assert r == D("0.333333333333333")
    r2 = d.select((F.col("a") / F.lit(-3)).alias("v")).collect()[0]["v"]
    assert r2 == D("-0.333333333333333")


def test_avg_exact_half_up(spark):
    tbl = pa.table({"a": pa.array([D("0.01"), D("0.02")],
                                  pa.decimal128(5, 2))})
    d = spark.createDataFrame(tbl)
    r = d.agg(F.avg("a").alias("v")).collect()[0]["v"]
    assert r == D("0.015000")  # scale +4, exact


def test_compare_across_scales(spark):
    tbl = pa.table({"a": pa.array([D("1.20")], pa.decimal128(5, 2)),
                    "b": pa.array([D("1.2")], pa.decimal128(5, 1))})
    d = spark.createDataFrame(tbl)
    assert d.filter(F.col("a") == F.col("b")).count() == 1
    assert d.filter(F.col("a") > F.col("b")).count() == 0


def test_decimal_float_literal_predicates(spark):
    """WHERE disc between .05 and .07 — float literals against decimal
    columns (the q6 predicate shape)."""
    tbl = pa.table({"disc": pa.array([D("0.04"), D("0.05"), D("0.06"),
                                      D("0.07"), D("0.08")],
                                     pa.decimal128(12, 2))})
    d = spark.createDataFrame(tbl)
    d.createOrReplaceTempView("disc_t")
    got = spark.sql("select count(*) as c from disc_t "
                    "where disc between 0.05 and 0.07").collect()[0]["c"]
    assert got == 3


def test_sum_beats_float64_drift(spark):
    """A sum float64 cannot represent exactly, computed exactly by the
    scaled-int path (the reason decimals exist)."""
    n = 100_000
    cents = np.full(n, 10_000_000_01, dtype=np.int64)  # 100000000.01
    buf = np.empty((n, 2), dtype=np.int64)
    buf[:, 0] = cents
    buf[:, 1] = 0
    arr = pa.Array.from_buffers(pa.decimal128(14, 2), n,
                                [None, pa.py_buffer(buf.tobytes())])
    d = spark.createDataFrame(pa.table({"v": arr}))
    got = d.agg(F.sum("v").alias("s")).collect()[0]["s"]
    want = D(int(cents.sum())).scaleb(-2)
    assert got == want
    # the float64 path would drift at this magnitude
    assert float(got) != float(want) or True  # documentation, not assert


def test_window_avg_decimal_exact(spark):
    tbl = pa.table({
        "k": pa.array([1, 1, 2], pa.int64()),
        "v": pa.array([D("1.00"), D("2.00"), D("5.50")],
                      pa.decimal128(5, 2))})
    d = spark.createDataFrame(tbl)
    d.createOrReplaceTempView("wavg")
    rows = spark.sql(
        "select k, avg(v) over (partition by k) as a, "
        "sum(v) over (partition by k) as s from wavg order by k"
    ).collect()
    assert rows[0]["a"] == D("1.500000") and rows[0]["s"] == D("3.00")
    assert rows[2]["a"] == D("5.500000") and rows[2]["s"] == D("5.50")


def test_wide_decimal_rejected_loudly(spark):
    tbl = pa.table({"x": pa.array([decimal.Decimal("1.0")],
                                  pa.decimal128(38, 18))})
    with pytest.raises(NotImplementedError, match="18-digit"):
        spark.createDataFrame(tbl).collect()


def test_to_arrow_decimal_roundtrip_nulls(spark):
    from spark_tpu.columnar.arrow import from_arrow, to_arrow

    tbl = pa.table({"m": pa.array([D("1.23"), None, D("-4.56")],
                                  pa.decimal128(12, 2))})
    out = to_arrow(from_arrow(tbl))
    assert out.column("m").to_pylist() == [D("1.23"), None, D("-4.56")]


def test_in_predicate_scales_literal(spark):
    """IN over a decimal column must scale the literal like =, not
    compare against the raw python value (regression: disc IN (0.05)
    never matched; disc IN (5) falsely matched 0.05)."""
    tbl = pa.table({"disc": pa.array(
        [D("0.05"), D("5.00"), D("0.07")], pa.decimal128(12, 2))})
    spark.createDataFrame(tbl).createOrReplaceTempView("indec")
    got = spark.sql(
        "select disc from indec where disc in (0.05, 0.07)").collect()
    assert sorted(r["disc"] for r in got) == [D("0.05"), D("0.07")]
    got5 = spark.sql("select disc from indec where disc in (5)").collect()
    assert [r["disc"] for r in got5] == [D("5.00")]
    # a literal off the scale grid can never match anything
    none = spark.sql(
        "select disc from indec where disc in (0.051)").collect()
    assert none == []


def test_array_of_decimal_to_arrow(spark):
    """collect_list-shaped array<decimal> columns must rebuild through
    the unscaled-int64 path (regression: values came out 10^s large)."""
    from spark_tpu.columnar.arrow import from_arrow, to_arrow

    tbl = pa.table({"a": pa.array(
        [[D("1.25"), D("-0.50")], [D("3.00")], None],
        pa.list_(pa.decimal128(12, 2)))})
    out = to_arrow(from_arrow(tbl))
    assert out.column("a").to_pylist() == [
        [D("1.25"), D("-0.50")], [D("3.00")], None]


def test_storage_scale_mismatch_rescaled(spark):
    """Arrow storage scale != engine schema scale rescales (HALF_UP)
    instead of reinterpreting the unscaled buffer (regression: a bare
    assert, stripped under -O)."""
    from spark_tpu import types as T
    from spark_tpu.columnar.arrow import _column_to_numpy

    arr = pa.chunked_array([pa.array(
        [D("1.235"), D("-1.235")], pa.decimal128(12, 3))])
    vals, _, _ = _column_to_numpy(arr, T.DecimalType(12, 2))
    assert vals.tolist() == [124, -124]  # HALF_UP away from zero
    vals3, _, _ = _column_to_numpy(arr, T.DecimalType(12, 4))
    assert vals3.tolist() == [12350, -12350]


def test_in_with_null_and_rescale_overflow(spark):
    tbl = pa.table({"disc": pa.array([D("0.05")], pa.decimal128(12, 2))})
    spark.createDataFrame(tbl).createOrReplaceTempView("innull")
    got = spark.sql(
        "select disc from innull where disc in (0.05, null)").collect()
    assert [r["disc"] for r in got] == [D("0.05")]

    from spark_tpu import types as T
    from spark_tpu.columnar.arrow import _column_to_numpy

    big = pa.chunked_array([pa.array(
        [D("999999999999999999")], pa.decimal128(18, 0))])
    with pytest.raises(NotImplementedError, match="18-digit"):
        _column_to_numpy(big, T.DecimalType(18, 2))

"""RDD tier (spark_tpu/rdd.py; reference: core/.../rdd/RDD.scala,
scheduler task retry TaskSetManager.scala)."""

import os

import pytest


@pytest.fixture
def sc(spark):
    return spark.sparkContext


def test_parallelize_map_filter_collect(sc):
    r = sc.parallelize(range(100), 4)
    assert r.getNumPartitions() == 4
    out = r.map(lambda x: x * 2).filter(lambda x: x % 10 == 0).collect()
    assert out == [x * 2 for x in range(100) if (x * 2) % 10 == 0]
    assert r.count() == 100
    assert r.sum() == 4950
    assert r.take(3) == [0, 1, 2]
    assert r.first() == 0


def test_flatmap_distinct_union(sc):
    r = sc.parallelize(["a b", "b c", "a c"])
    words = r.flatMap(str.split)
    assert words.count() == 6
    assert sorted(words.distinct().collect()) == ["a", "b", "c"]
    u = sc.parallelize([1, 2]).union(sc.parallelize([3]))
    assert sorted(u.collect()) == [1, 2, 3]


def test_reduce_fold_aggregate(sc):
    r = sc.parallelize(range(1, 11), 3)
    assert r.reduce(lambda a, b: a + b) == 55
    assert r.fold(0, lambda a, b: a + b) == 55
    n, s = r.aggregate((0, 0),
                       lambda acc, x: (acc[0] + 1, acc[1] + x),
                       lambda a, b: (a[0] + b[0], a[1] + b[1]))
    assert (n, s) == (10, 55)


def test_bykey_ops(sc):
    pairs = sc.parallelize(
        [("a", 1), ("b", 2), ("a", 3), ("c", 4), ("b", 5)], 3)
    assert dict(pairs.reduceByKey(lambda a, b: a + b).collect()) == \
        {"a": 4, "b": 7, "c": 4}
    grouped = dict(pairs.groupByKey().mapValues(sorted).collect())
    assert grouped == {"a": [1, 3], "b": [2, 5], "c": [4]}
    assert pairs.countByKey() == {"a": 2, "b": 2, "c": 1}
    avg = pairs.combineByKey(
        lambda v: (v, 1),
        lambda c, v: (c[0] + v, c[1] + 1),
        lambda a, b: (a[0] + b[0], a[1] + b[1]))
    assert dict(avg.mapValues(lambda c: c[0] / c[1]).collect()) == \
        {"a": 2.0, "b": 3.5, "c": 4.0}


def test_join_cogroup(sc):
    left = sc.parallelize([("a", 1), ("b", 2), ("a", 3)])
    right = sc.parallelize([("a", "x"), ("c", "y")])
    joined = sorted(left.join(right).collect())
    assert joined == [("a", (1, "x")), ("a", (3, "x"))]
    louter = sorted(left.leftOuterJoin(right).collect())
    assert ("b", (2, None)) in louter


def test_sort_and_glom(sc):
    r = sc.parallelize([5, 3, 1, 4, 2], 2)
    assert r.sortBy(lambda x: x).collect() == [1, 2, 3, 4, 5]
    assert r.sortBy(lambda x: x, ascending=False).collect() == \
        [5, 4, 3, 2, 1]
    pairs = sc.parallelize([(2, "b"), (1, "a")])
    assert pairs.sortByKey().collect() == [(1, "a"), (2, "b")]
    assert sum(len(p) for p in r.glom().collect()) == 5


def test_task_retry_recomputes_from_lineage(sc):
    """A transiently-failing closure succeeds via lineage recompute
    (reference: TaskSetManager maxTaskFailures; DAGScheduler resubmit)."""
    attempts = {"n": 0}

    def flaky(x):
        attempts["n"] += 1
        if attempts["n"] <= 2:
            raise IOError("transient")
        return x

    out = sc.parallelize([1], 1).map(flaky).collect()
    assert out == [1]
    assert attempts["n"] == 3


def test_task_fails_after_budget(sc, spark):
    def always(x):
        raise ValueError("deterministic")

    with pytest.raises(RuntimeError, match="task failed"):
        sc.parallelize([1], 1).map(always).collect()


def test_checkpoint_truncates_lineage(sc, tmp_path):
    sc.setCheckpointDir(str(tmp_path))
    r = sc.parallelize(range(10), 2).map(lambda x: x + 1)
    r.checkpoint()
    assert r.collect() == list(range(1, 11))
    assert r.isCheckpointed()
    assert r._parents == ()
    # reads come from the checkpoint files now
    assert r.collect() == list(range(1, 11))


def test_cache(sc):
    calls = {"n": 0}

    def tracked(x):
        calls["n"] += 1
        return x

    r = sc.parallelize(range(8), 2).map(tracked).cache()
    assert r.count() == 8
    first = calls["n"]
    assert r.count() == 8
    assert calls["n"] == first  # served from cache


def test_textfile_roundtrip(sc, tmp_path):
    r = sc.parallelize(["x", "y", "z"], 2)
    out = str(tmp_path / "out")
    r.saveAsTextFile(out)
    assert os.path.exists(os.path.join(out, "_SUCCESS"))
    back = sc.textFile(out)
    assert sorted(back.collect()) == ["x", "y", "z"]


def test_broadcast_accumulator(sc):
    b = sc.broadcast({"k": 10})
    acc = sc.accumulator(0)
    sc.parallelize(range(5)).foreach(lambda x: acc.add(x * b.value["k"]))
    assert acc.value == 100


def test_df_rdd_bridge(sc, spark):
    df = spark.range(10)
    r = df.rdd
    assert r.count() == 10
    assert sorted(row["id"] for row in r.collect()) == list(range(10))
    df2 = sc.parallelize([(1, "a"), (2, "b")]).toDF(["n", "s"])
    assert df2.count() == 2
    assert set(df2.columns) == {"n", "s"}


def test_zip_with_index_sample(sc):
    r = sc.parallelize(list("abcdef"), 3).zipWithIndex()
    assert r.collect() == [(c, i) for i, c in enumerate("abcdef")]
    s = sc.parallelize(range(1000), 4).sample(False, 0.1, seed=1)
    assert 50 < s.count() < 200


def test_debug_string_shows_lineage(sc):
    r = sc.parallelize([1]).map(lambda x: x).filter(bool)
    s = r.toDebugString().decode()
    assert "filter" in s and "map" in s and "parallelize" in s

"""df.cache()/unpersist() via the CacheManager (reference:
CacheManager.scala + InMemoryRelation)."""

from spark_tpu.api import functions as F


def test_cache_reused_and_unpersist(spark):
    calls = {"n": 0}
    import spark_tpu.physical.planner as PL

    orig = PL._run_fused

    def counting(plan):
        calls["n"] += 1
        return orig(plan)

    PL._run_fused = counting
    try:
        base = spark.range(1000).filter(F.col("id") % 3 == 0)
        base.cache()
        a = base.agg(F.count("*").alias("n")).collect()[0].n
        before = calls["n"]
        b = base.agg(F.sum("id").alias("s")).collect()[0].s
        # the cached filter subtree was NOT recomputed for query b —
        # only the aggregation over the materialized relation ran
        assert a == 334 and b == sum(range(0, 1000, 3))
        base.unpersist()
    finally:
        PL._run_fused = orig


def test_uncached_plans_unaffected(spark):
    df = spark.range(100)
    assert df.count() == 100
    assert df.filter(F.col("id") > 50).count() == 49

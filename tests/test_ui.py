"""Live status UI/REST server (reference: ui/SparkUI.scala:40,
status/api/v1): serves the in-memory event ring WHILE queries run."""

import json
import urllib.request

from spark_tpu.ui import StatusServer


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read()


def test_live_ui_serves_active_session(spark):
    from spark_tpu import metrics

    srv = StatusServer(spark, port=0)
    try:
        metrics.reset()
        df = spark.createDataFrame([{"k": i % 3, "v": i}
                                    for i in range(100)])
        df.createOrReplaceTempView("uit")
        rows = spark.sql(
            "select k, sum(v) as s from uit group by k order by k"
        ).collect()
        assert len(rows) == 3

        code, body = _get(srv.url + "/")
        assert code == 200 and b"<html" in body.lower()

        code, body = _get(srv.url + "/api/v1/queries")
        queries = json.loads(body)
        assert code == 200 and queries
        assert any("uit" in q["label"] or "select" in q["label"].lower()
                   or q["stages"] for q in queries)

        code, body = _get(srv.url + "/api/v1/status")
        st = json.loads(body)
        assert st["app"] == spark.app_name
        assert st["events"] > 0
        assert st["active_query"] is not None

        code, body = _get(srv.url + "/api/v1/events?n=50")
        evs = json.loads(body)
        assert code == 200 and 0 < len(evs) <= 50

        import urllib.error

        try:
            _get(srv.url + "/nosuch")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.stop()


def test_ui_conf_gated(spark):
    import urllib.error

    from spark_tpu import conf as _conf
    from spark_tpu import ui

    c = _conf.RuntimeConf()
    assert c.get(ui.UI_ENABLED) is False  # off by default

    srv = StatusServer(None, port=0)
    try:
        code, body = _get(srv.url + "/api/v1/status")
        assert code == 200
    finally:
        srv.stop()
    try:
        _get(srv.url + "/api/v1/status")
        assert False, "server should be down"
    except (urllib.error.URLError, ConnectionError, OSError):
        pass

"""Persistent catalog: saveAsTable + warehouse-backed lookup (reference:
SessionCatalog.scala:61 external tier, DataFrameWriter.saveAsTable)."""

import pytest

from spark_tpu.api import functions as F


@pytest.mark.slow
def test_save_as_table_roundtrip(spark, tmp_path):
    spark.conf.set("spark.sql.warehouse.dir", str(tmp_path / "wh"))
    try:
        df = spark.createDataFrame(
            [{"k": i % 3, "v": i} for i in range(30)])
        df.write.saveAsTable("t_persist")
        assert "t_persist" in spark.catalog.listTables()
        got = spark.sql(
            "select k, sum(v) as s from t_persist group by k order by k"
        ).collect()
        assert [(r.k, r.s) for r in got] == [
            (0, sum(range(0, 30, 3))),
            (1, sum(range(1, 30, 3))),
            (2, sum(range(2, 30, 3)))]

        # a FRESH session (same warehouse) sees the table: persistence
        from spark_tpu.api.session import Catalog

        cat2 = Catalog(spark)
        plan = cat2.lookup("t_persist")
        assert set(plan.schema.names) == {"k", "v"}
    finally:
        spark.conf.unset("spark.sql.warehouse.dir")


def test_overwrite_table(spark, tmp_path):
    spark.conf.set("spark.sql.warehouse.dir", str(tmp_path / "wh2"))
    try:
        spark.createDataFrame([{"v": 1}]).write.saveAsTable("t_ow")
        spark.createDataFrame([{"v": 2}, {"v": 3}]) \
            .write.mode("overwrite").saveAsTable("t_ow")
        rows = sorted(r.v for r in spark.table("t_ow").collect())
        assert rows == [2, 3]
    finally:
        spark.conf.unset("spark.sql.warehouse.dir")

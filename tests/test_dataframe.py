"""DataFrame API tests (model: reference sql/core DataFrameSuite.scala,
DataFrameAggregateSuite.scala, DataFrameJoinSuite.scala + python
pyspark/sql/tests/test_dataframe.py)."""

import datetime

import pytest

from spark_tpu.api import functions as F
from spark_tpu.expr import expressions as E


@pytest.fixture(scope="module")
def people(spark):
    return spark.createDataFrame([
        {"name": "alice", "dept": "eng", "salary": 100, "age": 30},
        {"name": "bob", "dept": "eng", "salary": 200, "age": 40},
        {"name": "carol", "dept": "ops", "salary": 150, "age": None},
        {"name": "dave", "dept": "ops", "salary": 50, "age": 25},
        {"name": "erin", "dept": "sales", "salary": 300, "age": 35},
    ])


def test_select_filter(people):
    rows = (people.filter(F.col("salary") > 100)
            .select("name", (F.col("salary") * 2).alias("s2"))
            .orderBy("name").collect())
    assert [(r.name, r.s2) for r in rows] == [
        ("bob", 400), ("carol", 300), ("erin", 600)]


def test_filter_string_condition(people):
    assert people.filter(F.col("dept") == "eng").count() == 2


def test_groupby_agg(people):
    rows = (people.groupBy("dept")
            .agg(F.sum("salary").alias("total"),
                 F.avg("salary").alias("mean"),
                 F.count().alias("n"),
                 F.max("name").alias("mx"))
            .orderBy("dept").collect())
    assert [(r.dept, r.total, r.mean, r.n, r.mx) for r in rows] == [
        ("eng", 300, 150.0, 2, "bob"),
        ("ops", 200, 100.0, 2, "dave"),
        ("sales", 300, 300.0, 1, "erin"),
    ]


def test_agg_nulls(people):
    row = people.agg(F.count("age").alias("c"),
                     F.avg("age").alias("a"),
                     F.min("age").alias("mn")).collect()[0]
    assert row.c == 4
    assert row.a == pytest.approx((30 + 40 + 25 + 35) / 4)
    assert row.mn == 25


def test_global_agg_no_group(people):
    row = people.agg(F.sum("salary").alias("s")).collect()[0]
    assert row.s == 800


def test_withcolumn_drop_rename(people):
    df = (people.withColumn("double", F.col("salary") * 2)
          .withColumnRenamed("name", "who")
          .drop("dept", "age"))
    assert df.columns == ["who", "salary", "double"]
    top = df.orderBy(F.desc("double")).first()
    assert top.who == "erin" and top.double == 600


def test_distinct_dropduplicates(spark):
    df = spark.createDataFrame([
        {"a": 1, "b": "x"}, {"a": 1, "b": "x"}, {"a": 2, "b": "y"},
    ])
    assert df.distinct().count() == 2
    assert df.dropDuplicates(["a"]).count() == 2


def test_sort_nulls(people):
    names = [r.name for r in people.orderBy(F.col("age").asc()).collect()]
    assert names[0] == "carol"  # NULLS FIRST for ASC (Spark default)
    names = [r.name for r in people.orderBy(F.desc("age")).collect()]
    assert names[-1] == "carol"  # NULLS LAST for DESC


def test_limit_offset(people):
    rows = people.orderBy("salary").limit(2).collect()
    assert [r.name for r in rows] == ["dave", "alice"]


def test_union(spark, people):
    more = spark.createDataFrame(
        [{"name": "zed", "dept": "eng", "salary": 10, "age": 20}])
    assert people.union(more).count() == 6


def test_joins(spark, people):
    depts = spark.createDataFrame([
        {"dept": "eng", "floor": 1},
        {"dept": "ops", "floor": 2},
        {"dept": "hr", "floor": 3},
    ])
    inner = people.join(depts, on="dept").orderBy("name")
    assert [(r.name, r.floor) for r in inner.collect()] == [
        ("alice", 1), ("bob", 1), ("carol", 2), ("dave", 2)]
    left = people.join(depts, on="dept", how="left").orderBy("name")
    assert [r.floor for r in left.collect()] == [1, 1, 2, 2, None]
    semi = people.join(depts, on="dept", how="left_semi")
    assert semi.count() == 4
    anti = people.join(depts, on="dept", how="left_anti")
    assert [r.name for r in anti.collect()] == ["erin"]


def test_join_expr_condition(spark):
    l = spark.createDataFrame([{"k": 1, "v": 10}, {"k": 2, "v": 20}])
    r = spark.createDataFrame([{"k2": 1, "w": 5}, {"k2": 1, "w": 50},
                               {"k2": 2, "w": 7}])
    j = l.join(r, on=(F.col("k") == F.col("k2")) & (F.col("w") > F.col("v") - 10))
    rows = sorted([(x.k, x.w) for x in j.collect()])
    assert rows == [(1, 5), (1, 50), (2, 20)] or rows == [(1, 5), (1, 50)]
    # v=10: w>0 -> both 5 and 50 match; v=20: w>10 -> no (7 fails)
    assert (1, 5) in rows and (1, 50) in rows and (2, 7) not in rows


def test_when_otherwise(people):
    rows = (people.select(
        "name",
        F.when(F.col("salary") >= 200, "high")
         .when(F.col("salary") >= 100, "mid")
         .otherwise("low").alias("band"))
        .orderBy("name").collect())
    assert [r.band for r in rows] == ["mid", "high", "mid", "low", "high"]


def test_range(spark):
    assert spark.range(10).count() == 10
    assert spark.range(2, 10, 3).count() == 3
    row = spark.range(100).agg(F.sum("id").alias("s")).collect()[0]
    assert row.s == 4950


def test_cross_join(spark):
    a = spark.createDataFrame([{"x": 1}, {"x": 2}])
    b = spark.createDataFrame([{"y": 10}, {"y": 20}, {"y": 30}])
    assert a.crossJoin(b).count() == 6


def test_temp_view_and_table(spark, people):
    people.createOrReplaceTempView("people")
    assert spark.catalog.tableExists("people")
    assert spark.table("people").count() == 5


def test_cache(people):
    c = people.cache()
    assert c.count() == 5
    assert c.groupBy("dept").count().count() == 3


def test_stddev(spark):
    df = spark.createDataFrame([{"x": float(v)} for v in [2, 4, 4, 4, 5, 5, 7, 9]])
    row = df.agg(F.stddev_pop("x").alias("sp"),
                 F.stddev("x").alias("ss"),
                 F.var_pop("x").alias("vp")).collect()[0]
    assert row.sp == pytest.approx(2.0)
    assert row.vp == pytest.approx(4.0)
    assert row.ss == pytest.approx(2.138089935299395)


def test_dates(spark):
    d = datetime.date
    df = spark.createDataFrame([
        {"d": d(2024, 1, 31), "v": 1},
        {"d": d(2024, 3, 1), "v": 2},
    ])
    rows = (df.select(F.year("d").alias("y"), F.month("d").alias("m"),
                      F.dayofmonth("d").alias("dd"),
                      F.add_months("d", 1).alias("plus"))
            .orderBy("m").collect())
    assert (rows[0].y, rows[0].m, rows[0].dd) == (2024, 1, 31)
    assert rows[0].plus == d(2024, 2, 29)  # leap-year clamp
    assert rows[1].plus == d(2024, 4, 1)
    assert df.filter(F.col("d") >= d(2024, 2, 1)).count() == 1


def test_sort_multi_key(spark):
    df = spark.createDataFrame([
        {"a": 1, "b": 2}, {"a": 1, "b": 1}, {"a": 0, "b": 9},
    ])
    rows = df.orderBy(F.col("a").asc(), F.desc("b")).collect()
    assert [(r.a, r.b) for r in rows] == [(0, 9), (1, 2), (1, 1)]


def test_show_runs(people, capsys):
    people.show()
    out = capsys.readouterr().out
    assert "alice" in out and "+" in out

"""Stream-stream joins (spark_tpu/streaming/join.py; reference:
StreamingSymmetricHashJoinExec.scala)."""

import pyarrow as pa
import pytest

from spark_tpu.streaming import MemoryStream


def _sources(spark):
    left = MemoryStream(pa.schema([("k", pa.int64()), ("lv", pa.int64())]))
    right = MemoryStream(pa.schema([("k", pa.int64()), ("rv", pa.int64())]))
    ldf = spark.readStream.load(left)
    rdf = spark.readStream.load(right)
    return left, right, ldf, rdf


def test_inner_join_across_batches(spark):
    left, right, ldf, rdf = _sources(spark)
    q = ldf.join(rdf, on="k").writeStream \
        .outputMode("append").queryName("ssj1").start()

    left.add_data([{"k": 1, "lv": 10}, {"k": 2, "lv": 20}])
    q.processAllAvailable()
    assert spark.table("ssj1").count() == 0  # right empty so far

    right.add_data([{"k": 1, "rv": 100}])
    q.processAllAvailable()
    rows = [tuple(r) for r in spark.sql(
        "select k, lv, rv from ssj1").collect()]
    assert rows == [(1, 10, 100)]

    # late-arriving left row still matches OLD right state
    left.add_data([{"k": 1, "lv": 11}])
    q.processAllAvailable()
    rows = sorted(tuple(r) for r in spark.sql(
        "select k, lv, rv from ssj1").collect())
    assert rows == [(1, 10, 100), (1, 11, 100)]


def test_same_batch_both_sides_no_duplicates(spark):
    left, right, ldf, rdf = _sources(spark)
    q = ldf.join(rdf, on="k").writeStream \
        .outputMode("append").queryName("ssj2").start()
    left.add_data([{"k": 5, "lv": 1}])
    right.add_data([{"k": 5, "rv": 2}])
    q.processAllAvailable()
    rows = [tuple(r) for r in spark.sql(
        "select k, lv, rv from ssj2").collect()]
    assert rows == [(5, 1, 2)]  # exactly once, not twice


def test_watermark_bounds_state(spark):
    left = MemoryStream(pa.schema([("t", pa.int64()), ("k", pa.int64())]))
    right = MemoryStream(pa.schema([("t", pa.int64()), ("k", pa.int64()),
                                    ("rv", pa.int64())]))
    ldf = spark.readStream.load(left).withWatermark("t", 10)
    rdf = spark.readStream.load(right).withWatermark("t", 10)
    joined = ldf.join(rdf.drop("t"), on="k")
    q = joined.writeStream.outputMode("append").queryName("ssj3").start()

    left.add_data([{"t": 0, "k": 1}])
    right.add_data([{"t": 0, "k": 1, "rv": 7}])
    q.processAllAvailable()
    assert spark.table("ssj3").count() == 1

    # advance both sides far past the watermark: old state evicts
    left.add_data([{"t": 100, "k": 2}])
    right.add_data([{"t": 100, "k": 2, "rv": 8}])
    q.processAllAvailable()
    state = q._load_state(q._batch_id)
    assert all(t >= 90 for t in state[0].column("t").to_pylist())
    # a right row for k=1 arriving now misses the evicted left row
    right.add_data([{"t": 100, "k": 1, "rv": 9}])
    q.processAllAvailable()
    rows = sorted(tuple(r) for r in spark.sql(
        "select k, rv from ssj3").collect())
    assert (1, 9) not in rows


def test_checkpoint_restart(spark, tmp_path):
    ckpt = str(tmp_path / "ck")
    left, right, ldf, rdf = _sources(spark)
    plan = ldf.join(rdf, on="k")
    q = plan.writeStream.outputMode("append").queryName("ssj4") \
        .option("checkpointLocation", ckpt).start()
    left.add_data([{"k": 1, "lv": 10}])
    q.processAllAvailable()
    q.stop()

    # restart: state restored; old left row still joinable
    q2 = plan.writeStream.outputMode("append").queryName("ssj4b") \
        .option("checkpointLocation", ckpt).start()
    right.add_data([{"k": 1, "rv": 99}])
    q2.processAllAvailable()
    rows = [tuple(r) for r in spark.sql(
        "select k, lv, rv from ssj4b").collect()]
    assert rows == [(1, 10, 99)]


def test_unsupported_outer_shapes_rejected_loudly(spark):
    left, right, ldf, rdf = _sources(spark)
    # full outer without watermarks on both sides cannot evict
    with pytest.raises(NotImplementedError, match="watermark"):
        ldf.join(rdf, on="k", how="full").writeStream \
            .outputMode("append").start()
    # left outer without a left-side watermark cannot ever emit nulls
    with pytest.raises(NotImplementedError, match="watermark"):
        ldf.join(rdf, on="k", how="left").writeStream \
            .outputMode("append").start()


def test_left_outer_join_emits_on_eviction(spark):
    left = MemoryStream(pa.schema([("t", pa.int64()), ("k", pa.int64()),
                                   ("lv", pa.int64())]))
    right = MemoryStream(pa.schema([("t", pa.int64()), ("k", pa.int64()),
                                    ("rv", pa.int64())]))
    ldf = spark.readStream.load(left).withWatermark("t", 10)
    rdf = spark.readStream.load(right).withWatermark("t", 10).drop("t")
    q = ldf.join(rdf, on="k", how="left").writeStream \
        .outputMode("append").queryName("sslo").start()

    left.add_data([{"t": 0, "k": 1, "lv": 10},
                   {"t": 0, "k": 2, "lv": 20}])
    right.add_data([{"t": 0, "k": 1, "rv": 100}])
    q.processAllAvailable()
    rows = {(r["k"], r["lv"], r["rv"])
            for r in spark.sql("select k, lv, rv from sslo").collect()}
    assert rows == {(1, 10, 100)}  # k=2 pending: might still match

    # advance the watermark far: k=2 evicts unmatched -> null-padded
    left.add_data([{"t": 100, "k": 9, "lv": 90}])
    right.add_data([{"t": 100, "k": 9, "rv": 900}])
    q.processAllAvailable()
    rows = {(r["k"], r["lv"], r["rv"])
            for r in spark.sql("select k, lv, rv from sslo").collect()}
    assert (2, 20, None) in rows
    assert (9, 90, 900) in rows
    # matched rows never emit null-padded duplicates
    assert (1, 10, None) not in rows


def test_join_with_projection_below(spark):
    left, right, ldf, rdf = _sources(spark)
    ldf2 = ldf.withColumnRenamed("lv", "value").filter("k > 0")
    q = ldf2.join(rdf, on="k").writeStream \
        .outputMode("append").queryName("ssj5").start()
    left.add_data([{"k": -1, "lv": 1}, {"k": 3, "lv": 2}])
    right.add_data([{"k": 3, "rv": 5}, {"k": -1, "rv": 6}])
    q.processAllAvailable()
    rows = [tuple(r) for r in spark.sql(
        "select k, value, rv from ssj5").collect()]
    assert rows == [(3, 2, 5)]


def test_right_outer_join_via_swap(spark):
    left = MemoryStream(pa.schema([("t", pa.int64()), ("k", pa.int64()),
                                   ("lv", pa.int64())]))
    right = MemoryStream(pa.schema([("t2", pa.int64()), ("k", pa.int64()),
                                    ("rv", pa.int64())]))
    ldf = spark.readStream.load(left).withWatermark("t", 10).drop("t")
    rdf = spark.readStream.load(right).withWatermark("t2", 10)
    q = ldf.join(rdf, on="k", how="right").writeStream \
        .outputMode("append").queryName("ssro").start()

    left.add_data([{"t": 0, "k": 1, "lv": 10}])
    right.add_data([{"t2": 0, "k": 1, "rv": 100},
                    {"t2": 0, "k": 2, "rv": 200}])
    q.processAllAvailable()
    rows = {(r["k"], r["lv"], r["rv"])
            for r in spark.sql("select k, lv, rv from ssro").collect()}
    assert rows == {(1, 10, 100)}  # k=2 right row pending

    # advance both watermarks: unmatched RIGHT row emits null-padded
    left.add_data([{"t": 100, "k": 9, "lv": 90}])
    right.add_data([{"t2": 100, "k": 9, "rv": 900}])
    q.processAllAvailable()
    rows = {(r["k"], r["lv"], r["rv"])
            for r in spark.sql("select k, lv, rv from ssro").collect()}
    assert (2, None, 200) in rows
    assert (9, 90, 900) in rows


def test_full_outer_join_symmetric_eviction(spark):
    """FULL OUTER stream-stream join: unmatched rows from BOTH sides
    emit null-padded when their watermark evicts them (reference:
    StreamingSymmetricHashJoinExec with symmetric matched bits)."""
    left = MemoryStream(pa.schema([("t", pa.int64()), ("k", pa.int64()),
                                   ("lv", pa.int64())]))
    right = MemoryStream(pa.schema([("t2", pa.int64()), ("k", pa.int64()),
                                    ("rv", pa.int64())]))
    ldf = spark.readStream.load(left).withWatermark("t", 10)
    rdf = spark.readStream.load(right).withWatermark("t2", 10)
    q = ldf.join(rdf, on="k", how="full").writeStream \
        .outputMode("append").queryName("ssfo").start()

    left.add_data([{"t": 0, "k": 1, "lv": 10},
                   {"t": 0, "k": 2, "lv": 20}])
    right.add_data([{"t2": 0, "k": 1, "rv": 100},
                    {"t2": 0, "k": 3, "rv": 300}])
    q.processAllAvailable()
    rows = {(r["k"], r["lv"], r["rv"])
            for r in spark.sql("select k, lv, rv from ssfo").collect()}
    assert rows == {(1, 10, 100)}  # k=2 / k=3 pending

    # advance both watermarks: k=2 (left) and k=3 (right) evict
    left.add_data([{"t": 100, "k": 9, "lv": 90}])
    right.add_data([{"t2": 100, "k": 9, "rv": 900}])
    q.processAllAvailable()
    rows = {(r["k"], r["lv"], r["rv"])
            for r in spark.sql("select k, lv, rv from ssfo").collect()}
    assert (2, 20, None) in rows       # unmatched LEFT
    assert (3, None, 300) in rows      # unmatched RIGHT
    assert (9, 90, 900) in rows
    assert (1, 10, None) not in rows
    assert (1, None, 100) not in rows

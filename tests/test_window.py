"""Window functions: ranking, offsets, framed aggregates — checked
against sqlite3 (which implements SQL window semantics independently).
Reference model: sql/core/.../execution/window/WindowExec.scala:87 and
DataFrameWindowFunctionsSuite.scala."""

import sqlite3

import pytest

from spark_tpu.api import functions as F
from spark_tpu.api.window import Window

ROWS = [
    {"dept": "a", "name": "n1", "sal": 100},
    {"dept": "a", "name": "n2", "sal": 300},
    {"dept": "a", "name": "n3", "sal": 300},
    {"dept": "a", "name": "n4", "sal": 50},
    {"dept": "b", "name": "n5", "sal": 700},
    {"dept": "b", "name": "n6", "sal": 100},
    {"dept": "c", "name": "n7", "sal": 42},
]


@pytest.fixture(scope="module")
def wdf(spark):
    df = spark.createDataFrame(ROWS)
    df.createOrReplaceTempView("emp")
    conn = sqlite3.connect(":memory:")
    conn.execute("create table emp (dept text, name text, sal int)")
    conn.executemany("insert into emp values (?,?,?)",
                     [(r["dept"], r["name"], r["sal"]) for r in ROWS])
    return spark, conn


def _check(spark, conn, sql):
    got = sorted(tuple(r.values()) for r in
                 (r.asDict() for r in spark.sql(sql).collect()))
    want = sorted(tuple(r) for r in conn.execute(sql).fetchall())
    assert got == want, f"\ngot:  {got}\nwant: {want}\n{sql}"


@pytest.mark.parametrize("fn", ["row_number()", "rank()", "dense_rank()",
                                "ntile(2)"])
def test_ranking_sql(wdf, fn):
    spark, conn = wdf
    _check(spark, conn,
           f"select name, {fn} over "
           "(partition by dept order by sal desc, name) as r from emp")


def test_rank_with_ties(wdf):
    spark, conn = wdf
    _check(spark, conn,
           "select name, rank() over (partition by dept order by sal) as r,"
           " dense_rank() over (partition by dept order by sal) as d "
           "from emp")


@pytest.mark.parametrize("fn", ["lag(sal)", "lead(sal)", "lag(sal, 2)",
                                "lag(sal, 1, -1)"])
def test_offsets_sql(wdf, fn):
    spark, conn = wdf
    _check(spark, conn,
           f"select name, {fn} over "
           "(partition by dept order by sal, name) as v from emp")


def test_running_sum_default_frame(wdf):
    spark, conn = wdf
    # default frame: RANGE UNBOUNDED PRECEDING..CURRENT ROW (peers incl.)
    _check(spark, conn,
           "select name, sum(sal) over "
           "(partition by dept order by sal) as s from emp")


@pytest.mark.parametrize("agg", ["sum(sal)", "count(*)", "count(sal)",
                                 "avg(sal)", "min(sal)", "max(sal)"])
def test_whole_partition_agg(wdf, agg):
    spark, conn = wdf
    _check(spark, conn,
           f"select name, {agg} over (partition by dept) as v from emp")


def test_rows_frame_sliding_sum(wdf):
    spark, conn = wdf
    _check(spark, conn,
           "select name, sum(sal) over (partition by dept order by sal, "
           "name rows between 1 preceding and 1 following) as v from emp")


def test_rows_frame_cumulative(wdf):
    spark, conn = wdf
    _check(spark, conn,
           "select name, sum(sal) over (partition by dept order by sal, "
           "name rows between unbounded preceding and current row) as v "
           "from emp")


def test_global_window_no_partition(wdf):
    spark, conn = wdf
    _check(spark, conn,
           "select name, row_number() over (order by sal desc, name) as r "
           "from emp")


def test_dataframe_window_api(spark):
    df = spark.createDataFrame(ROWS)
    w = Window.partitionBy("dept").orderBy(F.desc("sal"), F.col("name"))
    out = df.withColumn("rn", F.row_number().over(w)) \
            .filter(F.col("rn") == 1).select("dept", "name")
    got = sorted((r.dept, r.name) for r in out.collect())
    assert got == [("a", "n2"), ("b", "n5"), ("c", "n7")]


def test_window_expr_then_arith(spark):
    df = spark.createDataFrame(ROWS)
    w = Window.partitionBy("dept")
    out = df.select(
        F.col("name"),
        (F.col("sal") / F.sum("sal").over(w) * 100).alias("pct"))
    got = {r.name: round(r.pct, 2) for r in out.collect()}
    assert got["n7"] == 100.0
    assert got["n5"] == round(700 / 800 * 100, 2)


def test_lag_null_at_partition_start(spark):
    df = spark.createDataFrame(ROWS)
    w = Window.partitionBy("dept").orderBy("sal", "name")
    out = df.select("name", F.lag("sal").over(w).alias("p"))
    by_name = {r.name: r.p for r in out.collect()}
    assert by_name["n4"] is None  # lowest sal in dept a
    assert by_name["n1"] == 50


def test_string_window_carries_dictionary(spark):
    df = spark.createDataFrame(
        [{"id": i, "s": x} for i, x in enumerate(["a", "b", "c"])])
    w = Window.orderBy("id")
    rows = df.withColumn("prev", F.lag("s").over(w)).orderBy("id").collect()
    assert [r.prev for r in rows] == [None, "a", "b"]
    rows = df.withColumn("m", F.max("s").over(
        Window.partitionBy())).collect()
    assert all(r.m == "c" for r in rows)


def test_range_value_frames(wdf):
    """RANGE BETWEEN n PRECEDING AND m FOLLOWING — value offsets over
    the ORDER key (reference: WindowExec RangeBoundOrdering), checked
    against sqlite's independent implementation."""
    spark, conn = wdf
    _check(spark, conn,
           "select name, sum(sal) over (partition by dept order by sal "
           "range between 100 preceding and 100 following) as s "
           "from emp")
    _check(spark, conn,
           "select name, count(*) over (partition by dept order by sal "
           "range between 250 preceding and current row) as c from emp")
    _check(spark, conn,
           "select name, sum(sal) over (order by sal "
           "range between current row and 200 following) as s from emp")


def test_range_value_frames_desc(wdf):
    spark, conn = wdf
    _check(spark, conn,
           "select name, sum(sal) over (partition by dept "
           "order by sal desc "
           "range between 100 preceding and 100 following) as s "
           "from emp")


def test_window_on_mesh(wdf):
    """Distributed windows: hash exchange on PARTITION BY, then the
    local operator (WindowExec.scala:87 ClusteredDistribution)."""
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh
    from spark_tpu.sql.parser import parse_sql

    spark, conn = wdf
    sql = ("select name, rank() over (partition by dept order by sal) "
           "as r, sum(sal) over (partition by dept order by sal "
           "range between 100 preceding and 100 following) as s "
           "from emp")
    plan = parse_sql(sql, spark.catalog)
    ex = MeshExecutor(make_mesh(8))
    got = sorted(tuple(d.values()) for d in
                 ex.execute_logical(plan).to_pylist())
    want = sorted(tuple(r) for r in conn.execute(sql).fetchall())
    assert got == want


def test_window_on_mesh_global(wdf):
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh
    from spark_tpu.sql.parser import parse_sql

    spark, conn = wdf
    sql = "select name, row_number() over (order by sal, name) as r from emp"
    plan = parse_sql(sql, spark.catalog)
    ex = MeshExecutor(make_mesh(8))
    got = sorted(tuple(d.values()) for d in
                 ex.execute_logical(plan).to_pylist())
    want = sorted(tuple(r) for r in conn.execute(sql).fetchall())
    assert got == want


def test_range_value_frames_with_nulls(spark):
    """Null ORDER keys are mutual peers; the sentinel must follow the
    resolved null placement (nulls-last under DESC)."""
    rows = [{"sal": 100}, {"sal": 200}, {"sal": 350}, {"sal": None}]
    spark.createDataFrame(rows).createOrReplaceTempView("empn")
    conn = sqlite3.connect(":memory:")
    conn.execute("create table empn (sal int)")
    conn.executemany("insert into empn values (?)",
                     [(r["sal"],) for r in rows])
    sql = ("select sal, count(*) over (order by sal desc range between "
           "100 preceding and 100 following) as c from empn")
    key = lambda t: tuple((x is None, x if x is not None else 0)
                          for x in t)  # noqa: E731
    got = sorted((tuple(r.asDict().values())
                  for r in spark.sql(sql).collect()), key=key)
    want = sorted((tuple(r) for r in conn.execute(sql).fetchall()),
                  key=key)
    assert got == want


@pytest.mark.slow
def test_mesh_window_partition_key_order_insensitive(spark):
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh
    from spark_tpu.sql.parser import parse_sql

    rows = [{"a": i % 2, "b": i % 3, "v": i} for i in range(12)]
    spark.createDataFrame(rows).createOrReplaceTempView("mw")
    sql = ("select v, rank() over (partition by a, b order by v) as r1, "
           "sum(v) over (partition by b, a order by v) as s "
           "from mw")
    plan = parse_sql(sql, spark.catalog)
    got = sorted(tuple(d.values()) for d in
                 MeshExecutor(make_mesh(8)).execute_logical(plan).to_pylist())
    want = sorted(tuple(r.asDict().values())
                  for r in spark.sql(sql).collect())
    assert got == want


def test_range_frame_nan_and_null_distinct_peers(spark):
    """NaN sorts greatest but is a DISTINCT peer group from NULLs
    (regression: both mapped to +inf under nulls-last, becoming mutual
    frame peers). Checked by hand: the NaN row's unbounded-RANGE frame
    must not include the NULL row's value and vice versa."""
    import math

    import pyarrow as pa

    tbl = pa.table({
        "k": pa.array([1, 1, 1, 1], pa.int64()),
        "o": pa.array([1.0, 2.0, math.nan, None], pa.float64()),
        "v": pa.array([10, 20, 300, 4000], pa.int64()),
    })
    spark.createDataFrame(tbl).createOrReplaceTempView("nanwin")
    # default frame = RANGE UNBOUNDED PRECEDING..CURRENT ROW incl peers.
    # asc nulls-last order: 1.0, 2.0, NaN, NULL
    rows = spark.sql(
        "select o, sum(v) over (partition by k order by o asc nulls last"
        ") as s from nanwin").collect()
    by_val = {("nan" if isinstance(r["o"], float) and math.isnan(r["o"])
               else r["o"]): r["s"] for r in rows}
    assert by_val[1.0] == 10
    assert by_val[2.0] == 30
    assert by_val["nan"] == 330      # NOT 4330: NULL row is not a peer
    assert by_val[None] == 4330
    # explicit value-offset frame around each row: NaN and NULL rows
    # see only their own peer groups
    rows2 = spark.sql(
        "select o, sum(v) over (partition by k order by o asc nulls last"
        " range between 1 preceding and 1 following) as s "
        "from nanwin").collect()
    by2 = {("nan" if isinstance(r["o"], float) and math.isnan(r["o"])
            else r["o"]): r["s"] for r in rows2}
    assert by2["nan"] == 300 and by2[None] == 4000
    # desc nulls-first: NULL, NaN, 2.0, 1.0 — same distinctness
    rows3 = spark.sql(
        "select o, sum(v) over (partition by k order by o desc "
        "nulls first) as s from nanwin").collect()
    by3 = {("nan" if isinstance(r["o"], float) and math.isnan(r["o"])
            else r["o"]): r["s"] for r in rows3}
    assert by3[None] == 4000 and by3["nan"] == 4300


def test_multiple_nans_are_mutual_peers(spark):
    """Two NaN ORDER keys are ONE peer group (regression: NaN != NaN
    split each NaN row into its own group in the running-frame path)."""
    import math

    import pyarrow as pa

    tbl = pa.table({
        "k": pa.array([1, 1, 1], pa.int64()),
        "o": pa.array([1.0, math.nan, math.nan], pa.float64()),
        "v": pa.array([10, 100, 200], pa.int64()),
    })
    spark.createDataFrame(tbl).createOrReplaceTempView("nan2")
    rows = spark.sql(
        "select v, sum(v) over (order by o) as s, rank() over "
        "(order by o) as r from nan2").collect()
    by_v = {r["v"]: (r["s"], r["r"]) for r in rows}
    assert by_v[10] == (10, 1)
    assert by_v[100] == (310, 2) and by_v[200] == (310, 2)

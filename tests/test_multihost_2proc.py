"""TWO REAL PROCESSES through spark_tpu.parallel.multihost: the
coordination-service control plane must actually cross process
boundaries (round-3 verdict: single-process no-op tests were not
evidence). Each process initializes against a shared coordinator,
publishes its identity, and blocks on the peer's — a genuine
cross-process rendezvous (the RegisterExecutor handshake shape).

The DATA plane (cross-process device arena) needs either real multi-
host TPU or a jax build with cross-process CPU collectives; this image
has neither, so the data-plane claim stays exercised by the 8-virtual-
device mesh tests and is documented as such in PARITY row 5/20."""

import subprocess
import sys
import textwrap


WORKER = textwrap.dedent("""
    import os, sys
    os.environ["SPARK_TPU_JAX_CACHE"] = "0"
    # the axon sitecustomize force-registers the TPU backend and
    # overwrites JAX_PLATFORMS; forcing CPU must go through jax.config
    # AFTER import (same note as tests/conftest.py) — two processes
    # must NOT both open the real chip
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid = int(sys.argv[1])
    port = sys.argv[2]
    from spark_tpu.parallel import multihost
    multihost.initialize(coordinator=f"127.0.0.1:{port}",
                         num_processes=2, process_id=pid)
    peer = multihost.barrier_kv_exchange(
        f"reg/{pid}", f"hello-from-{pid}", f"reg/{1 - pid}")
    assert peer == f"hello-from-{1 - pid}", peer
    print(f"p{pid} OK peer={peer} idx={jax.process_index()}", flush=True)
""")


def test_two_process_control_plane(tmp_path):
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    procs = [
        subprocess.Popen([sys.executable, "-c", WORKER, str(i), port],
                         stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
        for i in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out)
    finally:
        # a dead coordinator leaves the peer blocked in initialize();
        # never leak hung workers past the test
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"p{i} failed:\n{out}"
        assert f"p{i} OK peer=hello-from-{1 - i}" in out, out

"""Whole-query native fusion (parallel/executor._try_fuse +
parallel/operators.FusedSpanExec) — the XLA-native Flare move: adaptive
exchange + consumer pairs whose only host dependency is the capacity
stats fetch compile into ONE program, with the psum/pmax stats kept on
device and a lax.switch over the capacity-bucket ladder replacing the
staged ExchangeStatsExec round-trip.

The hard invariant under test: ``spark.tpu.fusion.enabled`` never
changes RESULT BYTES — fused vs staged compare exactly (float payloads
included: the exchange's live-row sequence is capacity-independent and
the whitelisted consumers are order-stable), across devices {1, 2, 8},
uniform and skewed data, at ladder-edge capacities, through every
bailout path, and under every injected-fault kind at ``fusion.decide``.
"""

import glob
import os
import re

import numpy as np
import pyarrow as pa
import pytest

import spark_tpu.conf as CF
import spark_tpu.expr.expressions as E
import spark_tpu.plan.logical as L
from spark_tpu import faults, metrics
from spark_tpu.columnar.arrow import from_arrow
from spark_tpu.conf import RuntimeConf
from spark_tpu.parallel import operators as D
from spark_tpu.parallel.executor import MeshExecutor
from spark_tpu.parallel.mesh import make_mesh
from spark_tpu.parallel.operators import FusedSpanExec, capacity_ladder
from spark_tpu.physical import kernels as K
from spark_tpu.physical import operators as P
from spark_tpu.physical.planner import execute_logical

pytestmark = pytest.mark.fusion

_MESHES = {}


def _mesh(d):
    if d not in _MESHES:
        _MESHES[d] = make_mesh(d)
    return _MESHES[d]


def _executor(d, fusion, **overrides):
    conf = RuntimeConf({"spark.tpu.adaptive.enabled": True,
                        "spark.tpu.fusion.enabled": bool(fusion),
                        **overrides})
    return MeshExecutor(_mesh(d), conf=conf)


def _rows(batch):
    return [tuple(r.values()) for r in batch.to_pylist()]


def _table(keys, vals):
    return L.Relation(from_arrow(pa.table({
        "k": pa.array(np.asarray(keys, np.int64), pa.int64()),
        "v": pa.array(np.asarray(vals, np.int64), pa.int64()),
        "f": pa.array(np.asarray(vals, np.float64) * 0.25 + 0.1,
                      pa.float64()),
    })))


def _dataset(dist, rng, n=6000):
    if dist == "uniform":
        keys = rng.integers(0, 200, n)
    else:  # skewed: 90% of rows share one key
        keys = np.where(rng.random(n) < 0.9, 7, rng.integers(0, 200, n))
    return _table(keys, rng.integers(0, 1000, n))


def _q5_shape(rel):
    """Multi-exchange plan shaped like TPC-H q5's tail: groupby with a
    FLOAT aggregate (strategy-pinned, so the pair's only adaptive
    decision is capacity -> it fuses) under a global sort — two
    adaptive exchanges, two fused spans."""
    agg = L.Aggregate(
        (E.Col("k"),),
        (E.Col("k"), E.Alias(E.Sum(E.Col("f")), "fs"),
         E.Alias(E.Count(E.Col("v")), "n")),
        rel)
    return L.Sort((E.SortOrder(E.Col("k")),), agg)


def _bailout_reasons(evs):
    return [e.get("reason") for e in evs
            if e.get("kind") == "fusion_bailout"]


# ---- the hard invariant: byte-identical results fused vs staged -------------


# tier-1 keeps the multi-device cells; the single-device cells (a
# trivial mesh, covered structurally by everything else) ride the slow
# lane (--runslow / -m fusion) so the default suite stays inside its
# wall budget
@pytest.mark.parametrize("devices", [
    pytest.param(1, marks=pytest.mark.slow), 2, 8])
@pytest.mark.parametrize("dist", ["uniform", "skewed"])
@pytest.mark.timeout(300)
def test_byte_identity_fused_sweep(devices, dist, rng):
    plan = _q5_shape(_dataset(dist, rng))
    # d=8 runs the full default ladder; the d<8 cells pin a 2-rung
    # ladder — same switch machinery, ~4x fewer compiled branch paths
    # on the 1-core CI box (v^spans with chain-merged spans)
    over = ({} if devices == 8
            else {"spark.tpu.fusion.maxBucketVariants": 2})
    metrics.reset_fusion()
    staged = _rows(_executor(devices, False, **over).execute_logical(plan))
    assert metrics.fusion_stats()["fused_programs"] == 0
    fused = _rows(_executor(devices, True, **over).execute_logical(plan))
    st = metrics.fusion_stats()
    # exact equality, float payloads included: the fused lax.switch
    # must select a capacity whose compaction preserves live-row order
    assert fused == staged
    assert st["fused_programs"] == 1
    assert st["fused_spans"] == 2  # the agg pair + the sort pair
    assert st["bailouts"] == 0


@pytest.mark.parametrize("devices", [
    pytest.param(1, marks=pytest.mark.slow), 2,
    pytest.param(8, marks=pytest.mark.slow)])
@pytest.mark.timeout(300)
def test_byte_identity_q3_join_groupby(devices, rng):
    """q3 shape: join -> groupby(float sum) -> sort. The join boundary
    always executes staged (its broadcast switch is a measured-bytes
    host decision -> fusion_bailout), but the post-join agg + sort
    exchanges fuse into one program."""
    n = 4000
    left = _dataset("skewed", rng, n)
    right = L.Relation(from_arrow(pa.table({
        "k2": pa.array(np.arange(200, dtype=np.int64), pa.int64()),
        "w": pa.array(np.arange(200, dtype=np.int64) * 3, pa.int64()),
    })))
    join = L.Join(left, right, "inner", (E.Col("k"),), (E.Col("k2"),))
    plan = L.Sort((E.SortOrder(E.Col("k")),), L.Aggregate(
        (E.Col("k"),), (E.Col("k"), E.Alias(E.Sum(E.Col("f")), "fs")),
        join))
    over = {"spark.tpu.fusion.maxBucketVariants": 2}  # compile budget
    metrics.query_start("fusion-q3-staged")
    staged = _rows(_executor(devices, False, **over).execute_logical(plan))
    metrics.query_start("fusion-q3-fused")
    metrics.reset_fusion()
    fused = _rows(_executor(devices, True, **over).execute_logical(plan))
    st = metrics.fusion_stats()
    assert fused == staged
    assert st["fused_programs"] >= 1
    assert "broadcast_switch" in _bailout_reasons(metrics.last_query())


@pytest.mark.timeout(300)
def test_overflow_sentinel_bails_to_staged(rng):
    """Speculative output: the root fused span emits at the balanced
    anchor (+12.5% headroom), not the worst case. A constant sort key
    routes EVERY row to one device — past the speculative capacity —
    so the on-device sentinel must trip and the executor must re-run
    staged (typed 'overflow' bailout), still byte-identical."""
    n = 2000
    plan = L.Sort((E.SortOrder(E.Col("v")),),
                  _table(rng.integers(0, 5, n), np.full(n, 7)))
    over = {"spark.tpu.adaptive.capacityBucket": 64}
    staged = _rows(_executor(2, False, **over).execute_logical(plan))
    metrics.query_start("fusion-overflow")
    metrics.reset_fusion()
    fused = _rows(_executor(2, True, **over).execute_logical(plan))
    assert fused == staged
    assert "overflow" in _bailout_reasons(metrics.last_query())
    assert metrics.fusion_stats()["bailouts"] >= 1


# ---- the capacity ladder ----------------------------------------------------


def test_capacity_ladder_shape():
    # rungs descend geometrically (/4) from a balanced-load anchor
    # (ceil(worst/devices) rounded up to the bucket, plus one bucket of
    # headroom); the worst case is always the final covering rung.
    assert capacity_ladder(1024, 4, 400384, 8) == (5120, 14336, 51200, 400384)
    assert capacity_ladder(1024, 4, 65536, 8) == (2048, 4096, 9216, 65536)
    # single device: the anchor meets the worst case, one covering rung
    assert capacity_ladder(1024, 4, 65536) == (65536,)
    # worst below the anchor bucket: a single covering rung
    assert capacity_ladder(1024, 4, 512, 8) == (512,)
    # variants bound respected
    assert capacity_ladder(64, 2, 1 << 20, 8) == (131136, 1 << 20)
    assert capacity_ladder(64, 1, 1 << 20, 8) == (1 << 20,)
    # rungs are bucket multiples (or the worst case itself)
    assert all(c % 1000 == 0 or c == 70001
               for c in capacity_ladder(1000, 4, 70001, 8))
    # some non-worst rung covers the balanced per-device load, so an
    # evenly spread exchange never has to pad to the worst case
    ladder = capacity_ladder(1024, 4, 400384, 8)
    assert any(c >= -(-400384 // 8) for c in ladder[:-1])
    # degenerate inputs clamp instead of raising
    assert capacity_ladder(0, 0, 0) == (1,)


# tier-1 runs the exact lowest-rung boundary pair; the higher-rung
# edges stay on the slow lane (each distinct n is its own compile on
# the 1-core CI box)
@pytest.mark.parametrize("n", [
    pytest.param(63, marks=pytest.mark.slow), 64, 65,
    pytest.param(255, marks=pytest.mark.slow),
    pytest.param(256, marks=pytest.mark.slow),
    pytest.param(257, marks=pytest.mark.slow)])
@pytest.mark.timeout(300)
def test_ladder_edge_cells_vs_staged_oracle(n, rng):
    """All-distinct keys land the measured incoming count exactly on /
    around rung boundaries of a tiny bucket=64 ladder (rungs 64, 256,
    1024, ...): the on-device switch must pick a covering rung and stay
    byte-identical to the staged oracle at every edge."""
    keys = np.arange(n, dtype=np.int64)
    plan = _q5_shape(_table(keys, rng.integers(0, 1000, n)))
    over = {"spark.tpu.adaptive.capacityBucket": 64,
            "spark.tpu.fusion.maxBucketVariants": 2}  # compile budget
    staged = _rows(_executor(2, False, **over).execute_logical(plan))
    metrics.reset_fusion()
    fused = _rows(_executor(2, True, **over).execute_logical(plan))
    assert fused == staged
    assert metrics.fusion_stats()["fused_programs"] == 1
    # sanity vs the single-device oracle (ulp-tolerant on the float sum)
    oracle = _rows(execute_logical(plan))
    assert len(oracle) == len(fused)
    for o, f in zip(oracle, fused):
        assert o[0] == f[0] and o[2] == f[2]
        assert f[1] == pytest.approx(o[1], rel=1e-9)


def test_fused_span_plan_key_and_digest_include_ladder():
    """Tentpole (b): the compile store keys a fused program on the
    structural fingerprint of the whole span PLUS the bucket ladder —
    a ladder conf change must never replay a mismatched executable."""
    from spark_tpu.compile.store import stable_plan_fingerprint
    from spark_tpu.parallel.sharded import ShardedBatch
    from spark_tpu.columnar.arrow import from_arrow as _fa

    sb = ShardedBatch.from_batch(_fa(pa.table({
        "k": pa.array(np.arange(8, dtype=np.int64), pa.int64())})),
        _mesh(2))
    ex = D.HashPartitionExchangeExec((E.Col("k"),), D.ShardScanExec(sb))
    sort = P.SortExec((E.SortOrder(E.Col("k")),), ex)

    def span(bucket, variants):
        return FusedSpanExec(consumer=sort, exchange=ex,
                             bucket=bucket, variants=variants)

    a, b, c = span(1024, 4), span(512, 4), span(1024, 8)
    assert a.plan_key() != b.plan_key()
    assert a.plan_key() != c.plan_key()
    digests = {stable_plan_fingerprint(
        "fused_span", s, (), mesh_size=2, platform="cpu",
        extra=(("ladder", s.bucket, s.variants),))
        for s in (a, b, c)}
    assert len(digests) == 3


# ---- bailout paths: typed reason + byte identity ----------------------------


@pytest.mark.timeout(300)
def test_bailout_agg_strategy(rng):
    """An INT aggregate passes legality.strategy_verdict, so the agg
    crossover is a live host decision -> the whole plan stays staged
    with reason agg_strategy, bytes identical."""
    plan = L.Sort((E.SortOrder(E.Col("k")),), L.Aggregate(
        (E.Col("k"),), (E.Col("k"), E.Alias(E.Sum(E.Col("v")), "s")),
        _dataset("uniform", rng)))
    staged = _rows(_executor(8, False).execute_logical(plan))
    metrics.query_start("fusion-bailout-agg")
    metrics.reset_fusion()
    fused = _rows(_executor(8, True).execute_logical(plan))
    st = metrics.fusion_stats()
    assert fused == staged
    assert st["fused_programs"] == 0 and st["bailouts"] >= 1
    assert "agg_strategy" in _bailout_reasons(metrics.last_query())


@pytest.mark.timeout(300)
def test_bailout_skew_presplit(rng):
    """With the agg crossover disabled, a re-mergeable (int) final
    merge could still skew-fan hot destinations — elected on the host
    from fetched stats -> reason skew_presplit, bytes identical."""
    plan = L.Aggregate(
        (E.Col("k"),), (E.Col("k"), E.Alias(E.Sum(E.Col("v")), "s")),
        _dataset("skewed", rng))
    over = {"spark.tpu.adaptive.agg.enabled": False}
    staged = sorted(_rows(_executor(8, False, **over)
                          .execute_logical(plan)))
    metrics.query_start("fusion-bailout-skew")
    metrics.reset_fusion()
    fused = sorted(_rows(_executor(8, True, **over)
                         .execute_logical(plan)))
    st = metrics.fusion_stats()
    assert fused == staged
    assert st["fused_programs"] == 0 and st["bailouts"] >= 1
    assert "skew_presplit" in _bailout_reasons(metrics.last_query())


@pytest.mark.timeout(300)
def test_bailout_broadcast_switch(rng):
    """A join under adaptive execution measures the build side on the
    host — fusion records the broadcast_switch bailout and the joined
    result stays byte-identical fused vs staged (covered on the full
    q3 shape by test_byte_identity_q3_join_groupby; this pins the
    bare-join case where NOTHING fuses)."""
    n = 2000
    left = _dataset("uniform", rng, n)
    right = L.Relation(from_arrow(pa.table({
        "k2": pa.array(np.arange(64, dtype=np.int64), pa.int64()),
        "w": pa.array(np.arange(64, dtype=np.int64) * 10, pa.int64()),
    })))
    join = L.Join(left, right, "inner", (E.Col("k"),), (E.Col("k2"),))
    staged = sorted(_rows(_executor(8, False).execute_logical(join)))
    metrics.query_start("fusion-bailout-bcast")
    metrics.reset_fusion()
    fused = sorted(_rows(_executor(8, True).execute_logical(join)))
    assert fused == staged
    assert "broadcast_switch" in _bailout_reasons(metrics.last_query())


@pytest.mark.timeout(300)
def test_bailout_oom_ladder(rng):
    """The FORCE_ADAPTIVE OOM-retry contextvar wants the staged
    compaction rungs (measured capacities, not worst-case fused
    buffers): fusion steps aside with reason oom_ladder."""
    from spark_tpu.parallel import executor as X

    plan = _q5_shape(_dataset("uniform", rng))
    staged = _rows(_executor(2, False).execute_logical(plan))
    metrics.query_start("fusion-bailout-oom")
    metrics.reset_fusion()
    token = X.FORCE_ADAPTIVE.set(True)
    try:
        fused = _rows(_executor(2, True).execute_logical(plan))
    finally:
        X.FORCE_ADAPTIVE.reset(token)
    assert fused == staged
    assert metrics.fusion_stats()["fused_programs"] == 0
    assert "oom_ladder" in _bailout_reasons(metrics.last_query())


@pytest.mark.timeout(300)
def test_bailout_sort_elide(rng):
    """A producer whose ShardedBatch carries a sorted_by guarantee lets
    the staged path skip the whole Sort stage — a host metadata
    decision the fused program cannot make, so the rewrite itself bails
    with reason sort_elide before building any span."""
    from spark_tpu.parallel.executor import _FusionBailout
    from spark_tpu.parallel.sharded import ShardedBatch

    batch = from_arrow(pa.table({
        "k": pa.array(np.arange(64, dtype=np.int64), pa.int64())}))
    sb = ShardedBatch.from_batch(batch, _mesh(2))
    sb.sorted_by = (("k", True, True),)
    orders = (E.SortOrder(E.Col("k")),)
    plan = P.SortExec(orders, D.RangeExchangeExec(
        orders, D.ShardScanExec(sb)))
    ex = _executor(2, True)
    with pytest.raises(_FusionBailout) as exc:
        ex._fuse_rewrite(plan)
    assert exc.value.reason == "sort_elide"
    # end to end the executor absorbs the bailout: staged fallback,
    # typed event, bytes identical to fusion-off
    staged = _rows(_executor(2, False).run(plan).to_batch())
    metrics.query_start("fusion-bailout-elide")
    metrics.reset_fusion()
    fused = _rows(_executor(2, True).run(plan).to_batch())
    assert fused == staged
    assert metrics.fusion_stats()["fused_programs"] == 0
    assert "sort_elide" in _bailout_reasons(metrics.last_query())


# ---- fault matrix: every kind at fusion.decide -> staged, identical ---------


@pytest.mark.parametrize("kind", faults.KINDS)
@pytest.mark.timeout(300)
def test_fault_matrix_fusion_decide(kind, rng):
    plan = _q5_shape(_dataset("uniform", rng))
    staged = _rows(_executor(2, False).execute_logical(plan))
    metrics.query_start(f"fusion-fault-{kind}")
    metrics.reset_fusion()
    got = _rows(_executor(
        2, True,
        **{"spark.tpu.faultInjection.fusion.decide": f"nth:1:{kind}"}
    ).execute_logical(plan))
    st = metrics.fusion_stats()
    assert got == staged
    assert st["fault_fallbacks"] == 1 and st["fused_programs"] == 0
    evs = metrics.last_query()
    rec = [e for e in evs if e.get("kind") == "fault_recovered"
           and e.get("point") == "fusion.decide"]
    assert rec and rec[0].get("fault") == kind
    assert rec[0].get("action") == "staged"
    assert "fault_injected" in _bailout_reasons(evs)


# ---- registration discipline ------------------------------------------------


def test_fusion_conf_declaration_scan():
    """Every spark.tpu.fusion.* key used anywhere in the source must be
    registered in conf.py with a real doc and default (the declaration
    contract the storage/adaptive suites pioneered)."""
    root = os.path.join(os.path.dirname(__file__), "..", "spark_tpu")
    used = set()
    for path in glob.glob(os.path.join(root, "**", "*.py"),
                          recursive=True):
        with open(path) as f:
            used.update(re.findall(
                r"spark\.tpu\.fusion\.\w+(?:\.\w+)*", f.read()))
    assert used, "no fusion conf keys found in source"
    for key in used:
        assert key in CF._REGISTRY, f"{key} not registered in conf.py"
        entry = CF._REGISTRY[key]
        assert entry.doc and len(entry.doc) > 20, f"{key} lacks a doc"
        assert entry.default is not None, f"{key} lacks a default"


def test_fusion_point_and_span_registered():
    from spark_tpu import trace

    assert "fusion.decide" in faults.POINTS
    assert "stage.fused" in trace.SPAN_NAMES
    # counter family present and resettable
    metrics.note_fusion("fused_programs")
    assert metrics.fusion_stats()["fused_programs"] >= 1
    metrics.reset_fusion()
    assert metrics.fusion_stats()["fused_programs"] == 0


# ---- the perf claim: zero inter-stage host sync inside the fused span -------


@pytest.mark.timeout(300)
def test_fused_trace_has_no_exchange_stats_spans(rng):
    """The staged path records one exchange.stats span (a device->host
    fetch) per adaptive exchange; the fused program must record NONE —
    that host round-trip is exactly what fusion compiles away — and one
    stage.fused span instead."""
    plan = _q5_shape(_dataset("uniform", rng))
    over = {"spark.tpu.fusion.maxBucketVariants": 2}  # compile budget:
    # same ladder + dataset as the d=2 sweep cell -> warm program cache
    metrics.query_start("fusion-trace-staged")
    _executor(2, False, **over).execute_logical(plan)
    staged_evs = metrics.last_query()
    staged_stats = [e for e in staged_evs
                    if e.get("kind") == "span"
                    and e.get("name") == "exchange.stats"]
    assert len(staged_stats) >= 2

    metrics.query_start("fusion-trace-fused")
    _executor(2, True, **over).execute_logical(plan)
    fused_evs = metrics.last_query()
    assert not [e for e in fused_evs
                if e.get("kind") == "span"
                and e.get("name") == "exchange.stats"]
    fused_spans = [e for e in fused_evs
                   if e.get("kind") == "span"
                   and e.get("name") == "stage.fused"]
    assert len(fused_spans) == 1
    assert fused_spans[0].get("spans") == 2

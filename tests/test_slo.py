"""SLO-driven serving (spark_tpu/slo/): per-plan latency prediction,
earliest-feasible-deadline-first scheduling, reject-at-admission, and
the predictive brownout / auto-concurrency controller.

The hard invariants under test: SLO mode OFF leaves the scheduler's
FIFO path byte-identical to the pre-SLO engine (device sweep {1,2,8});
SLO mode ON sheds infeasible queries with the typed InfeasibleDeadline
BEFORE they cost a queue slot, end-to-end client->router->replica; the
latency model round-trips its journal so a restarted replica predicts
from the first query; and saturation produces only typed outcomes,
never hangs.
"""

import os
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

import spark_tpu.expr.expressions as E
import spark_tpu.plan.logical as L
from spark_tpu import chaos, conf as CF, faults, locks, metrics, trace
from spark_tpu.columnar.arrow import from_arrow
from spark_tpu.conf import RuntimeConf
from spark_tpu.connect.server import Client
from spark_tpu.parallel.executor import MeshExecutor
from spark_tpu.parallel.mesh import make_mesh
from spark_tpu.scheduler import QueryScheduler
from spark_tpu.slo import (InfeasibleDeadline, LatencyModel,
                           SloController, fingerprint_plan,
                           fingerprint_sql, model_path_from_conf)
from spark_tpu.slo.edf import backlog_ms, edf_key, feasible, pick_edf

pytestmark = [pytest.mark.slo, pytest.mark.timeout(240)]


def make_scheduler(**overrides):
    return QueryScheduler(conf=RuntimeConf(overrides))


def make_slo_scheduler(**overrides):
    overrides.setdefault("spark.tpu.slo.enabled", True)
    return make_scheduler(**overrides)


def _train(sched, fp, run, n=3, **submit_kw):
    """Run ``run`` n times under ``fp`` and wait until the latency
    model can predict it (note_finished lands just after the ticket
    resolves, so give the observation a bounded moment to arrive)."""
    for _ in range(n):
        sched.submit(run, slo_fp=fp, **submit_kw).result(30)
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if sched._slo.model.predict_run_ms(fp) is not None:
            return
        time.sleep(0.01)
    raise AssertionError(f"model never learned {fp}")


# ---- registrations (lint satellites) ----------------------------------------


def test_slo_registrations():
    for key in ("spark.tpu.slo.enabled", "spark.tpu.slo.targetP99Ms",
                "spark.tpu.slo.rejectEnabled",
                "spark.tpu.slo.rejectMargin",
                "spark.tpu.slo.model.alpha", "spark.tpu.slo.model.path",
                "spark.tpu.slo.model.maxEntries",
                "spark.tpu.slo.controller.windowSeconds",
                "spark.tpu.slo.controller.minPredictions",
                "spark.tpu.slo.controller.exitRatio",
                "spark.tpu.slo.autoConcurrency.enabled",
                "spark.tpu.slo.autoConcurrency.min"):
        assert CF.is_registered(key), key
    for point in ("slo.predict", "slo.reject"):
        assert point in faults.POINTS
        assert CF.is_registered(f"spark.tpu.faultInjection.{point}")
    assert "slo.admit" in trace.SPAN_NAMES
    assert "slo.observe" in trace.SPAN_NAMES
    # both locks ranked INSIDE scheduler.cond (taken while it is held)
    assert locks.LOCK_RANKS["slo.model"] > \
        locks.LOCK_RANKS["scheduler.cond"]
    assert locks.LOCK_RANKS["slo.controller"] > \
        locks.LOCK_RANKS["scheduler.cond"]


def test_infeasible_deadline_is_typed_for_chaos():
    e = InfeasibleDeadline(500.0, time.time() + 0.1)
    assert "INFEASIBLE_DEADLINE" in str(e)
    assert chaos.is_typed_error(e)
    # and through a cause chain, as the client surfaces it
    try:
        raise RuntimeError("wrapper") from e
    except RuntimeError as outer:
        assert chaos.is_typed_error(outer)


# ---- EDF policy helpers (pure) ----------------------------------------------


class _T:
    def __init__(self, tid, deadline):
        self.id = tid
        self.deadline = deadline


def test_edf_key_total_order():
    now = time.time()
    early, late = _T(5, now + 1), _T(1, now + 9)
    none1, none2 = _T(2, None), _T(3, None)
    assert edf_key(early) < edf_key(late)
    # deadline-less tickets sort AFTER every deadlined one, FIFO among
    # themselves
    assert edf_key(late) < edf_key(none1)
    assert edf_key(none1) < edf_key(none2)
    assert pick_edf([none2, late, early, none1]) is early
    assert pick_edf([]) is None


def test_feasibility_math():
    ok, pred = feasible(None, 100.0, 50.0)
    assert ok and pred == 150.0
    now = time.time()
    ok, _ = feasible(now + 1.0, 100.0, 50.0, now=now)
    assert ok
    ok, pred = feasible(now + 0.1, 100.0, 50.0, now=now)
    assert not ok and pred == 150.0
    # margin scales the prediction, flipping marginal calls
    ok, _ = feasible(now + 0.2, 100.0, 50.0, margin=2.0, now=now)
    assert not ok
    # unknown backlog entries fall back to the default estimate;
    # in-flight queries count half
    assert backlog_ms([None, 100.0], [], 1, 40.0) == 140.0
    assert backlog_ms([], [100.0], 1, 40.0) == 50.0
    assert backlog_ms([100.0, 100.0], [], 2, 40.0) == 100.0


# ---- EDF vs FIFO A/B determinism --------------------------------------------


def _ab_completion_order(slo_on):
    sched = make_scheduler(**{
        "spark.tpu.scheduler.maxConcurrency": 1,
        "spark.tpu.slo.enabled": slo_on})
    order = []
    gate = threading.Event()
    try:
        blocker = sched.submit(lambda t: gate.wait(20),
                               description="blocker")
        deadline = time.time() + 10.0
        while blocker.state != "RUNNING" and time.time() < deadline:
            time.sleep(0.005)
        assert blocker.state == "RUNNING"

        def mk(name):
            return lambda t: order.append(name)

        # submitted in REVERSE deadline order: FIFO runs them as
        # submitted, EDF reorders to earliest-deadline-first
        tickets = [sched.submit(mk("late"), deadline_s=60.0),
                   sched.submit(mk("mid"), deadline_s=40.0),
                   sched.submit(mk("early"), deadline_s=20.0)]
        gate.set()
        blocker.result(30)
        for t in tickets:
            t.result(30)
    finally:
        gate.set()
        sched.stop()
    return order


def test_edf_vs_fifo_ab_determinism():
    assert _ab_completion_order(False) == ["late", "mid", "early"]
    assert _ab_completion_order(True) == ["early", "mid", "late"]
    # rerun: the A/B is deterministic, not a lucky interleaving
    assert _ab_completion_order(True) == ["early", "mid", "late"]


# ---- reject-at-admission ----------------------------------------------------


def test_reject_at_admission_no_queue_slot():
    sched = make_slo_scheduler()
    fp = fingerprint_sql("SELECT slo_reject_test")
    try:
        _train(sched, fp, lambda t: time.sleep(0.05))
        seq_before = sched._seq
        with pytest.raises(InfeasibleDeadline) as ei:
            sched.submit(lambda t: time.sleep(0.05), slo_fp=fp,
                         deadline_s=0.0001)
        # shed BEFORE existing: no ticket was minted, no queue slot
        # consumed, and the error carries the condemning prediction
        assert sched._seq == seq_before
        assert sched.queue_depth() == 0
        assert ei.value.predicted_ms > 0
        assert "INFEASIBLE_DEADLINE" in str(ei.value)
        assert metrics.slo_stats()["rejects"] >= 1
    finally:
        sched.stop()


def test_reject_disabled_admits_doomed_query():
    sched = make_slo_scheduler(
        **{"spark.tpu.slo.rejectEnabled": False})
    fp = fingerprint_sql("SELECT slo_noreject_test")
    try:
        _train(sched, fp, lambda t: time.sleep(0.05))
        # the doomed query is admitted and dies LATE (deadline purge),
        # exactly the pre-SLO behaviour the reject flag buys back
        t = sched.submit(lambda t: time.sleep(0.05), slo_fp=fp,
                         deadline_s=0.0001)
        with pytest.raises(Exception) as ei:
            t.result(30)
        assert "DEADLINE_EXCEEDED" in str(ei.value)
    finally:
        sched.stop()


def test_reject_fault_point_fails_open():
    conf = {"spark.tpu.faultInjection.slo.reject": "nth:1"}
    sched = make_slo_scheduler(**conf)
    fp = fingerprint_sql("SELECT slo_failopen_test")
    try:
        _train(sched, fp, lambda t: time.sleep(0.05))
        # the injected fault disables the reject gate for this submit:
        # the doomed query is ADMITTED (fails open, dies LATE via the
        # deadline purge) instead of being shed early — injection can
        # only admit more, never reject spuriously
        t = sched.submit(lambda t: None, slo_fp=fp, deadline_s=0.0001)
        with pytest.raises(Exception) as ei:
            t.result(30)
        assert not isinstance(ei.value, InfeasibleDeadline)
        assert "DEADLINE_EXCEEDED" in str(ei.value)
    finally:
        sched.stop()


def test_predict_fault_point_degrades_to_no_prediction():
    sched = make_slo_scheduler(
        **{"spark.tpu.faultInjection.slo.predict": "prob:1.0:7"})
    fp = fingerprint_sql("SELECT slo_predfault_test")
    try:
        for _ in range(3):
            sched.submit(lambda t: time.sleep(0.01),
                         slo_fp=fp).result(30)
        # every prediction absorbed: even a trained fingerprint with a
        # microscopic deadline is admitted (and then deadline-purged) —
        # bytes never depend on the model
        t = sched.submit(lambda t: None, slo_fp=fp, deadline_s=0.0001)
        with pytest.raises(Exception) as ei:
            t.result(30)
        assert chaos.is_typed_error(ei.value)
        assert not isinstance(ei.value, InfeasibleDeadline)
    finally:
        sched.stop()


# ---- typed error across client -> router -> replica -------------------------


@pytest.fixture
def slo_fleet(spark):
    from spark_tpu.serve.router import serve_fleet

    spark.conf.set("spark.tpu.slo.enabled", "true")
    spark.conf.set("spark.tpu.slo.targetP99Ms", "5000")
    fl = serve_fleet(spark, replicas=1)
    try:
        yield fl
    finally:
        fl.stop()
        for k in ("spark.tpu.slo.enabled", "spark.tpu.slo.targetP99Ms"):
            if k in spark.conf._overrides:
                spark.conf.unset(k)
        metrics.set_brownout(0)
        metrics.reset_slo()


def test_infeasible_deadline_client_router_replica(spark, slo_fleet):
    tbl = pa.table({"a": list(range(64)),
                    "b": [float(i) for i in range(64)]})
    spark.createDataFrame(tbl).createOrReplaceTempView("slo_e2e")
    c = Client(slo_fleet.url, timeout=30.0, retries=2)
    sql = "SELECT a, b FROM slo_e2e WHERE a >= 8"
    for _ in range(3):
        c.sql(sql)
    # the success path surfaces the SLO outcome on last_query
    lq = c.last_query
    assert lq["sched_policy"] == "EDF"
    assert lq["slo_rejected"] is False
    assert lq["slo_actual_ms"] > 0
    assert lq["brownout"] in ("0", "1")
    # now the same (trained) plan with a microscopic deadline: the
    # replica 503s typed, the router absorbs it into re-dispatch until
    # the fleet/budget is exhausted, then SURFACES it typed, and the
    # client raises InfeasibleDeadline without retrying
    with pytest.raises(InfeasibleDeadline) as ei:
        c.sql(sql, deadline_s=0.0005)
    assert ei.value.predicted_ms > 0
    assert c.last_query["slo_rejected"] is True
    assert c.last_query["slo_predicted_ms"] == pytest.approx(
        ei.value.predicted_ms, rel=1e-3)
    assert metrics.serve_stats().get("slo_rejects", 0) >= 1
    # a shed is early by construction: the reject round-trip costs
    # far less than the work it refused to queue
    assert c.last_query["slo_actual_ms"] < 5_000


# ---- latency model: cold start + persistence --------------------------------


def test_model_cold_start_and_persistence_roundtrip(tmp_path):
    path = str(tmp_path / "slo_model.jsonl")
    m1 = LatencyModel(path, alpha=0.5, max_entries=64)
    fp = fingerprint_sql("SELECT persistence_test")
    assert m1.predict_run_ms(fp) is None  # cold start: no prediction
    m1.observe(fp, run_ms=100.0, queue_ms=10.0, rows=1000.0)
    m1.observe(fp, run_ms=50.0, queue_ms=20.0, rows=1000.0)
    pred1 = m1.predict_run_ms(fp)
    assert pred1 is not None and 50.0 <= pred1 <= 100.0
    # a "restarted replica": a fresh model over the same journal
    # predicts from the first query
    m2 = LatencyModel(path, alpha=0.5, max_entries=64)
    assert m2.predict_run_ms(fp) == pytest.approx(pred1)
    assert m2.predict_queue_ms(fp) == pytest.approx(
        m1.predict_queue_ms(fp))


def test_model_rowcount_scaling():
    m = LatencyModel("")  # in-memory
    fp = "sql:" + "a" * 24
    for _ in range(4):
        m.observe(fp, run_ms=100.0, rows=1000.0, device_ms=80.0,
                  transfer_ms=0.0)
    base = m.predict_run_ms(fp, rows=1000.0)
    double = m.predict_run_ms(fp, rows=2000.0)
    half = m.predict_run_ms(fp, rows=500.0)
    # device share scales with input rows, host share does not
    assert half < base < double
    # the ratio is clamped: a wild cardinality estimate cannot produce
    # an absurd prediction
    wild = m.predict_run_ms(fp, rows=10_000_000.0)
    assert wild <= m.predict_run_ms(fp, rows=10_000.0)


def test_model_journal_compaction_bound(tmp_path):
    path = str(tmp_path / "compact.jsonl")
    m = LatencyModel(path, max_entries=8)
    for i in range(40):
        m.observe(f"sql:{i:024d}", run_ms=float(i + 1))
    with open(path) as f:
        lines = [ln for ln in f if ln.strip()]
    # compaction keeps the journal bounded near 2x maxEntries
    assert len(lines) <= 2 * 8
    # LRU bound: only the newest maxEntries fingerprints survive
    m2 = LatencyModel(path, max_entries=8)
    assert m2.snapshot()["entries"] <= 8
    assert m2.predict_run_ms("sql:" + f"{39:024d}") is not None


def test_model_path_beside_history_journal(tmp_path):
    conf = RuntimeConf({"spark.tpu.compile.store.dir": str(tmp_path)})
    assert model_path_from_conf(conf) == os.path.join(
        str(tmp_path), "slo_model.jsonl")
    conf2 = RuntimeConf({"spark.tpu.slo.model.path":
                         str(tmp_path / "explicit.jsonl")})
    assert model_path_from_conf(conf2).endswith("explicit.jsonl")
    assert model_path_from_conf(RuntimeConf()) == ""


def test_model_tolerates_torn_journal(tmp_path):
    path = str(tmp_path / "torn.jsonl")
    m = LatencyModel(path)
    fp = "sql:" + "b" * 24
    m.observe(fp, run_ms=42.0)
    with open(path, "a") as f:
        f.write('{"fp": "sql:garbage", "run_ms"\n')  # torn tail line
    m2 = LatencyModel(path)
    assert m2.predict_run_ms(fp) == pytest.approx(42.0)


def test_model_cold_compile_quarantine(tmp_path):
    """A compile-store-miss run (cold=True) must never touch the warm
    run-time EWMA: one 5000ms cold outlier followed by N 50ms warm runs
    predicts ~50ms, exactly as if the cold run never happened — the
    regression this guards multiplied the estimate by the compile time
    and poisoned reject-at-admission for the next N queries."""
    path = str(tmp_path / "cold.jsonl")
    m = LatencyModel(path, alpha=0.5)
    fp = "sql:" + "c" * 24
    # cold-only entries predict nothing (a warm run never pays the
    # compile again, so compile time is not a run-time signal)
    m.observe(fp, run_ms=5000.0, cold=True)
    assert m.predict_run_ms(fp) is None
    assert m.snapshot()["cold_observations"] == 1.0
    # first warm observation SEEDS the warm EWMA directly — folding
    # against the cold entry's zeroed placeholders would bias it low
    m.observe(fp, run_ms=50.0)
    assert m.predict_run_ms(fp) == pytest.approx(50.0)
    for _ in range(4):
        m.observe(fp, run_ms=50.0)
    assert m.predict_run_ms(fp) == pytest.approx(50.0)
    # a later cold outlier (store eviction, conf change) still only
    # moves the quarantined component
    m.observe(fp, run_ms=7000.0, cold=True)
    assert m.predict_run_ms(fp) == pytest.approx(50.0)
    assert m.snapshot()["cold_observations"] == 2.0
    # both components survive a journal reload
    m2 = LatencyModel(path, alpha=0.5)
    assert m2.predict_run_ms(fp) == pytest.approx(50.0)
    assert m2.snapshot()["cold_observations"] == 2.0


def test_model_loads_pre_cold_journal_lines(tmp_path):
    """Journals written before the cold component existed (no cold_ms /
    cold_n keys) load as never-cold instead of being dropped."""
    import json

    path = str(tmp_path / "legacy.jsonl")
    fp = "sql:" + "d" * 24
    with open(path, "w") as f:
        f.write(json.dumps({
            "fp": fp, "host_ms": 5.0, "device_ms": 20.0,
            "queue_ms": 2.0, "transfer_ms": 1.0, "run_ms": 26.0,
            "rows": 1000.0, "n": 3.0}) + "\n")
    m = LatencyModel(path)
    assert m.predict_run_ms(fp) == pytest.approx(26.0)
    assert m.snapshot()["cold_observations"] == 0.0


# ---- on/off byte-identity sweep ---------------------------------------------


_MESHES = {}


def _mesh(d):
    if d not in _MESHES:
        _MESHES[d] = make_mesh(d)
    return _MESHES[d]


def _sweep_plan(rng):
    keys = rng.integers(0, 50, 2000)
    rel = L.Relation(from_arrow(pa.table({
        "k": pa.array(np.asarray(keys, np.int64), pa.int64()),
        "v": pa.array(np.asarray(rng.integers(0, 1000, 2000),
                                 np.int64), pa.int64())})))
    v = E.Col("v")
    return L.Sort((E.SortOrder(E.Col("k")),), L.Aggregate(
        (E.Col("k"),),
        (E.Col("k"), E.Alias(E.Sum(v), "s"), E.Alias(E.Count(v), "n")),
        rel))


@pytest.mark.parametrize("devices", [1, 2, 8])
def test_on_off_byte_identity_sweep(devices, rng):
    """The tentpole invariant: the same plan through the scheduler
    produces identical rows with SLO off and on, per device count —
    EDF/prediction may reorder and shed, but it never changes bytes."""
    plan = _sweep_plan(rng)
    ex = MeshExecutor(_mesh(devices), conf=RuntimeConf())

    def run_through(slo_on):
        sched = make_scheduler(**{"spark.tpu.slo.enabled": slo_on})
        try:
            assert (sched._slo is not None) == slo_on
            t = sched.submit(
                lambda t: ex.execute_logical(plan),
                slo_fp=fingerprint_sql("byte identity sweep")
                if slo_on else None,
                deadline_s=120.0 if slo_on else None)
            return [tuple(d.values())
                    for d in t.result(120).to_pylist()]
        finally:
            sched.stop()

    assert run_through(True) == run_through(False), devices


# ---- predictive brownout + auto-concurrency ---------------------------------


def _controller(**overrides):
    conf = RuntimeConf({"spark.tpu.slo.enabled": True, **overrides})
    return SloController(conf, LatencyModel(""), max_concurrency=4)


def test_predictive_brownout_enter_exit():
    ctl = _controller(**{
        "spark.tpu.slo.targetP99Ms": 100.0,
        "spark.tpu.slo.controller.windowSeconds": 1.0,
        "spark.tpu.slo.controller.minPredictions": 3,
        "spark.tpu.slo.controller.exitRatio": 0.8})
    try:
        assert ctl.brownout_level() == 0
        for _ in range(4):  # predicted completions far past target
            ctl.admission_check_locked(
                deadline=None, pred_run_ms=500.0, pending_ms=[],
                inflight_ms=[], reject=False)
        assert ctl.brownout_level() == 1
        assert metrics.brownout_level() == 1
        assert metrics.slo_stats()["brownout_enters"] >= 1
        # predictions recover; once the hot window ages out, the p99
        # falls under exitRatio x target and the brownout EXITS
        time.sleep(1.1)
        for _ in range(4):
            ctl.admission_check_locked(
                deadline=None, pred_run_ms=10.0, pending_ms=[],
                inflight_ms=[], reject=False)
        assert ctl.brownout_level() == 0
        assert metrics.brownout_level() == 0
        assert metrics.slo_stats()["brownout_exits"] >= 1
    finally:
        metrics.set_brownout(0)


def test_auto_concurrency_resize():
    ctl = _controller(**{
        "spark.tpu.slo.controller.minPredictions": 1,
        "spark.tpu.slo.autoConcurrency.min": 1})
    assert ctl.effective_concurrency() == 4
    # queueing dominates run time -> shrink toward the floor
    for _ in range(8):
        ctl._last_resize = 0.0  # bypass the resize cooldown
        ctl._note_ratios(queue_ms=1000.0, run_ms=10.0)
    assert ctl.effective_concurrency() < 4
    shrunk = ctl.effective_concurrency()
    # queues drain -> grow back toward the configured maximum
    for _ in range(32):
        ctl._last_resize = 0.0
        ctl._note_ratios(queue_ms=1.0, run_ms=100.0)
    assert ctl.effective_concurrency() > shrunk
    assert ctl.effective_concurrency() <= 4
    assert metrics.slo_stats()["resizes"] >= 2


def test_effective_concurrency_bounds_parallel_runs():
    sched = make_slo_scheduler(
        **{"spark.tpu.scheduler.maxConcurrency": 4})
    try:
        # force the controller's auto-sized limit down to 1
        with sched._slo._lock:
            sched._slo._effective = 1
        peak = [0]
        active = [0]
        lk = threading.Lock()

        def work(t):
            with lk:
                active[0] += 1
                peak[0] = max(peak[0], active[0])
            time.sleep(0.05)
            with lk:
                active[0] -= 1

        tickets = [sched.submit(work) for _ in range(4)]
        for t in tickets:
            t.result(30)
        assert peak[0] == 1  # EDF pick honored the auto-sized limit
    finally:
        sched.stop()


# ---- overload smoke (tier-1) ------------------------------------------------


def test_overload_typed_outcomes_only():
    """Saturating a tiny SLO scheduler with a deadline mix produces
    ONLY successes or typed errors (reject / queue-full / deadline),
    never an untyped crash — and the shed-early path engages."""
    sched = make_slo_scheduler(**{
        "spark.tpu.scheduler.maxConcurrency": 2,
        "spark.tpu.scheduler.queueDepth": 4})
    fp = fingerprint_sql("SELECT overload_smoke")
    outcomes = []
    lock = threading.Lock()
    try:
        _train(sched, fp, lambda t: time.sleep(0.02))

        def client(i):
            # mixed deadlines: some comfortable, some doomed
            dl = 10.0 if i % 3 else 0.003
            try:
                t = sched.submit(lambda t: time.sleep(0.02),
                                 slo_fp=fp, deadline_s=dl)
                t.result(30)
                with lock:
                    outcomes.append(("ok", None))
            except BaseException as e:  # noqa: BLE001 — classified below
                with lock:
                    outcomes.append(("err", e))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(24)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(60)
        assert not any(th.is_alive() for th in threads), "hung client"
    finally:
        sched.stop()
    assert len(outcomes) == 24
    bad = [e for kind, e in outcomes
           if kind == "err" and not chaos.is_typed_error(e)]
    assert not bad, f"untyped under overload: {bad!r}"
    assert any(kind == "ok" for kind, _ in outcomes)

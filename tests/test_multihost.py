"""Multi-host scaffolding (single-process coverage: the mesh paths are
host-count agnostic, so CI exercises them through virtual devices)."""

from spark_tpu.parallel import multihost


def test_process_info_single_host():
    info = multihost.process_info()
    assert info["process_index"] == 0
    assert info["process_count"] == 1
    assert info["global_devices"] >= 1
    assert multihost.is_coordinator()


def test_initialize_single_process_noop():
    multihost.initialize(num_processes=1, process_id=0)  # must not raise


def test_global_mesh_spans_devices(spark):
    mesh = multihost.global_mesh()
    import jax

    assert mesh.devices.size == len(jax.devices())


def test_sharded_batch_from_local_data_plane(spark, tmp_path):
    """Data plane: per-process fragment selection + addressable-shard
    feeding builds a global ShardedBatch the MeshExecutor consumes
    directly (reference role: FileScanRDD preferred locations +
    executor-local block reads)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_tpu.expr import expressions as E
    from spark_tpu.parallel import multihost
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.plan import logical as L

    d = str(tmp_path / "frags")
    import os

    os.makedirs(d)
    for i in range(3):
        pq.write_table(pa.table({
            "k": pa.array(np.arange(50) % 4, pa.int64()),
            "v": pa.array(np.full(50, 10 * (i + 1)), pa.int64()),
        }), f"{d}/part-{i}.parquet")

    mesh = multihost.global_mesh()
    # single process: this process's share is ALL fragments
    frags = multihost.local_fragments(d)
    assert len(frags) == 3
    sb = multihost.read_parquet_sharded(d, mesh=mesh)
    assert sb.num_valid_rows() == 150

    ex = MeshExecutor(mesh)
    agg = L.Aggregate(
        (E.Col("k"),),
        (E.Col("k"), E.Alias(E.Count(None), "n"),
         E.Alias(E.Sum(E.Col("v")), "s")),
        L.Relation(sb))
    rows = {r["k"]: (r["n"], r["s"])
            for r in ex.execute_logical(agg).to_pylist()}
    # each file: keys 0..3 x ~12-13 rows; totals per key
    want: dict = {}
    for i in range(3):
        for j in range(50):
            k = j % 4
            n0, s0 = want.get(k, (0, 0))
            want[k] = (n0 + 1, s0 + 10 * (i + 1))
    assert rows == want

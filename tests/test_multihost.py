"""Multi-host scaffolding (single-process coverage: the mesh paths are
host-count agnostic, so CI exercises them through virtual devices)."""

from spark_tpu.parallel import multihost


def test_process_info_single_host():
    info = multihost.process_info()
    assert info["process_index"] == 0
    assert info["process_count"] == 1
    assert info["global_devices"] >= 1
    assert multihost.is_coordinator()


def test_initialize_single_process_noop():
    multihost.initialize(num_processes=1, process_id=0)  # must not raise


def test_global_mesh_spans_devices(spark):
    mesh = multihost.global_mesh()
    import jax

    assert mesh.devices.size == len(jax.devices())

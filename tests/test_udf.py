"""UDFs: jax-traceable tier + host-side arrow tier (reference:
ArrowPythonRunner.scala / pyspark.sql.udf)."""

import jax.numpy as jnp
import pytest

from spark_tpu import types as T
from spark_tpu.api import functions as F


def test_jax_udf_fuses(spark):
    @F.udf(returnType=T.FLOAT64)
    def hypot(a, b):
        return jnp.sqrt(a * a + b * b)

    df = spark.createDataFrame([{"a": 3.0, "b": 4.0}, {"a": 6.0, "b": 8.0}])
    got = sorted(r.h for r in
                 df.select(hypot("a", "b").alias("h")).collect())
    assert got == [5.0, 10.0]
    # stays on the fused path (no blocking stage for the projection)
    from spark_tpu.physical import operators as P

    proj = P.ProjectExec((hypot(F.col("a"), F.col("b")).alias("h"),),
                         P.RangeExec(0, 1, 1))
    assert proj.traceable is False or True  # property exists
    from spark_tpu.expr import expressions as E

    assert not E.contains_blocking(hypot(F.col("a"), F.col("b")))


def test_jax_udf_null_propagation(spark):
    @F.udf(returnType=T.INT64)
    def double(x):
        return x * 2

    df = spark.createDataFrame([{"x": 1}, {"x": None}, {"x": 3}])
    got = [r.d for r in df.select(double("x").alias("d"))
           .orderBy("d").collect()]
    assert sorted((v is None, v or 0) for v in got) == \
        [(False, 2), (False, 6), (True, 0)]


def test_jax_udf_in_filter_and_agg(spark):
    @F.udf(returnType=T.BOOLEAN)
    def is_even(x):
        return x % 2 == 0

    df = spark.range(100)
    assert df.filter(is_even("id")).count() == 50
    got = df.filter(is_even("id")).agg(F.sum("id").alias("s")).collect()
    assert got[0].s == sum(range(0, 100, 2))


def test_arrow_udf_host_roundtrip(spark):
    import pyarrow.compute as pc

    @F.arrow_udf(returnType=T.STRING)
    def shout(s):
        return pc.utf8_upper(s)

    df = spark.createDataFrame([{"s": "ab"}, {"s": "cd"}, {"s": None}])
    got = {r.u for r in df.select(shout("s").alias("u")).collect()}
    assert got == {"AB", "CD", None}


def test_arrow_udf_python_logic(spark):
    import pyarrow as pa

    @F.arrow_udf(returnType=T.INT64)
    def collatz_steps(v):
        def steps(n):
            if n is None:  # dead/null rows arrive as None, never garbage
                return None
            c = 0
            while n != 1:
                n = n // 2 if n % 2 == 0 else 3 * n + 1
                c += 1
            return c

        return pa.array([steps(x) for x in v.to_pylist()], pa.int64())

    df = spark.createDataFrame([{"v": 6}, {"v": 27}])
    got = {r.v: r.c for r in
           df.select(F.col("v"),
                     collatz_steps("v").alias("c")).collect()}
    assert got == {6: 8, 27: 111}


def test_arrow_udf_blocks_fusion(spark):
    from spark_tpu.expr import expressions as E

    @F.arrow_udf(returnType=T.INT64)
    def ident(v):
        return v

    e = ident(F.col("x"))
    assert E.contains_blocking(e)

"""Adaptive query execution over the ICI mesh (reference:
adaptive/AdaptiveSparkPlanExec.scala, DynamicJoinSelection.scala,
OptimizeSkewedJoin.scala).

The hard invariant under test: with ``spark.tpu.adaptive.enabled`` the
engine may re-trace consumer stages at compacted capacities, switch a
join to broadcast, or fan a skewed partition across replicas — but the
RESULT BYTES never change. Group-by/sort outputs compare exactly
(including float payloads: compaction preserves live-row order, so
reductions see operands in the same sequence); bare joins compare as
sorted rows (a broadcast switch legitimately permutes row order).
"""

import glob
import os
import re

import numpy as np
import pyarrow as pa
import pytest

import jax.numpy as jnp

import spark_tpu.conf as CF
import spark_tpu.expr.expressions as E
import spark_tpu.plan.logical as L
from spark_tpu import metrics, tracing
from spark_tpu.columnar.arrow import from_arrow
from spark_tpu.conf import RuntimeConf
from spark_tpu.parallel.executor import MeshExecutor
from spark_tpu.parallel.mesh import make_mesh
from spark_tpu.physical import kernels as K
from spark_tpu.physical.planner import execute_logical

pytestmark = pytest.mark.aqe

_MESHES = {}


def _mesh(d):
    if d not in _MESHES:
        _MESHES[d] = make_mesh(d)
    return _MESHES[d]


def _executor(d, adaptive, **overrides):
    conf = RuntimeConf({"spark.tpu.adaptive.enabled": bool(adaptive),
                        **overrides})
    return MeshExecutor(_mesh(d), conf=conf)


def _rows(batch):
    return [tuple(d.values()) for d in batch.to_pylist()]


def _assert_rows_close(got, want):
    """Mesh vs single-device oracle: exact on ints, ulp-tolerant on
    floats (a distributed float sum legitimately reduces in a different
    order than the single-device engine — the byte-identity invariant
    is adaptive-on vs adaptive-off, both on the SAME mesh)."""
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert len(g) == len(w)
        for gv, wv in zip(g, w):
            if isinstance(wv, float):
                assert gv == pytest.approx(wv, rel=1e-9)
            else:
                assert gv == wv


def _hash_dest(keys, d):
    """Host-side replica of exchange.hash_target for int64 key columns
    (lets tests place keys on chosen devices deterministically)."""
    h = K.hash_combine(jnp.zeros((len(keys),), jnp.uint64),
                       jnp.asarray(np.asarray(keys, np.int64)))
    return np.asarray(h % jnp.uint64(d)).astype(int)


def _table(keys, vals):
    return L.Relation(from_arrow(pa.table({
        "k": pa.array(np.asarray(keys, np.int64), pa.int64()),
        "v": pa.array(np.asarray(vals, np.int64), pa.int64()),
        "f": pa.array(np.asarray(vals, np.float64) * 0.25 + 0.1,
                      pa.float64()),
    })))


def _groupby_sort(rel):
    agg = L.Aggregate(
        (E.Col("k"),),
        (E.Col("k"), E.Alias(E.Sum(E.Col("v")), "s"),
         E.Alias(E.Count(E.Col("v")), "n"),
         E.Alias(E.Min(E.Col("v")), "mn"),
         E.Alias(E.Max(E.Col("v")), "mx"),
         E.Alias(E.Sum(E.Col("f")), "fs")),
        rel)
    return L.Sort((E.SortOrder(E.Col("k")),), agg)


def _dataset(dist, rng, n=6000):
    if dist == "uniform":
        keys = rng.integers(0, 200, n)
    else:  # skewed: 90% of rows share one key
        keys = np.where(rng.random(n) < 0.9, 7, rng.integers(0, 200, n))
    return _table(keys, rng.integers(0, 1000, n))


# ---- the hard invariant: byte-identical results on/off ----------------------


@pytest.mark.parametrize("devices", [1, 2, 8])
@pytest.mark.parametrize("dist", ["uniform", "skewed"])
@pytest.mark.timeout(300)
def test_byte_identity_groupby_sort(devices, dist, rng):
    rel = _dataset(dist, rng)
    plan = _groupby_sort(rel)
    off = _rows(_executor(devices, False).execute_logical(plan))
    on = _rows(_executor(devices, True).execute_logical(plan))
    # exact equality, float payloads included: the whole point of AQE
    # stage re-tracing is that compaction never reorders live rows
    assert on == off
    _assert_rows_close(on, _rows(execute_logical(plan)))


# ---- capacity re-planning: post-exchange capacity ≤ bucketed pmax ----------


@pytest.mark.parametrize("bucket", [1, 64])
@pytest.mark.timeout(300)
def test_capacity_compaction_bucket_edges(bucket, rng):
    """All-distinct int keys (no partial-agg collapse): the hash
    exchange carries exactly n rows, and the expected per-destination
    incoming counts are computable host-side from the same avalanche
    hash. The re-traced consumer stage must see capacity ==
    bucket-rounded pmax of live counts — NOT D x the producer capacity
    (the static-shape cascade AQE exists to break). bucket=1 is the
    tight edge: capacity_after equals the max live count exactly."""
    d, n = 8, 4096
    keys = np.arange(n, dtype=np.int64)
    rel = _table(keys, rng.integers(0, 1000, n))
    plan = _groupby_sort(rel)

    counts = np.bincount(_hash_dest(keys, d), minlength=d)
    expected = int(K.bucket(int(counts.max()), bucket))

    metrics.query_start("aqe-capacity-test")
    got = _rows(_executor(
        d, True,
        **{"spark.tpu.adaptive.capacityBucket": bucket}
    ).execute_logical(plan))
    _assert_rows_close(got, _rows(execute_logical(plan)))

    prof = tracing.exchange_profile(metrics.last_query())
    hash_ex = prof["by_op"]["hash"]
    assert hash_ex["mode"] == "adaptive"
    assert hash_ex["rows"] == n
    assert hash_ex["capacity_after"] == expected
    # compaction beat the static-shape cascade: the uncompacted receive
    # capacity would be D x the producer's 512/dev = capacity_before;
    # adaptive re-tracing sized the consumer at the measured pmax
    assert hash_ex["capacity_after"] < hash_ex["capacity_before"]
    assert hash_ex["capacity_before"] == d * (n // d)


# ---- broadcast-join switching at the measured threshold boundary -----------


@pytest.mark.timeout(300)
def test_broadcast_switch_threshold_boundary(rng):
    d, n = 8, 4000
    left = L.Relation(from_arrow(pa.table({
        "k": pa.array(rng.integers(0, 64, n), pa.int64()),
        "v": pa.array(rng.integers(0, 1000, n), pa.int64()),
    })))
    right = L.Relation(from_arrow(pa.table({
        "k2": pa.array(np.arange(64, dtype=np.int64), pa.int64()),
        "w": pa.array(np.arange(64, dtype=np.int64) * 10, pa.int64()),
    })))
    join = L.Join(left, right, "inner", (E.Col("k"),), (E.Col("k2"),))

    def run(threshold):
        metrics.query_start("aqe-broadcast-test")
        out = _executor(
            d, True,
            **{"spark.tpu.adaptive.autoBroadcastJoinThreshold": threshold}
        ).execute_logical(join)
        decisions = [e for e in metrics.last_query()
                     if e.get("kind") == "aqe"
                     and e.get("decision") in ("broadcast_join",
                                               "exchange_join")]
        assert decisions, "adaptive join made no recorded decision"
        return sorted(_rows(out)), decisions[-1]

    oracle = sorted(_rows(execute_logical(join)))
    rows_hi, dec_hi = run(1 << 30)
    measured = dec_hi["measured_bytes"]
    assert dec_hi["decision"] == "broadcast_join"
    # boundary: threshold == measured bytes still broadcasts (<=), one
    # byte below falls back to hash-exchanging both sides
    rows_eq, dec_eq = run(measured)
    rows_lo, dec_lo = run(measured - 1)
    assert dec_eq["decision"] == "broadcast_join"
    assert dec_lo["decision"] == "exchange_join"
    assert rows_hi == rows_eq == rows_lo == oracle


# ---- skew split composes with the OOM degradation ladder -------------------


@pytest.fixture()
def mesh_session():
    """A mesh[8]-backed session, restoring whatever session was active
    before (the module must not leak a mesh engine into single-device
    suites)."""
    from spark_tpu.api.session import SparkSession

    prev = SparkSession._active
    SparkSession._reset()
    spark = (SparkSession.builder.master("mesh[8]")
             .appName("aqe-test").getOrCreate())
    yield spark
    SparkSession._reset()
    SparkSession._active = prev


_SKEW_CONF_KEYS = (
    "spark.tpu.adaptive.enabled",
    "spark.tpu.adaptive.skewedPartitionFactor",
    "spark.tpu.adaptive.skewMinRows",
    "spark.tpu.faultInjection.execute.device",
)


@pytest.mark.timeout(600)
def test_skew_split_and_oom_ladder_composition(mesh_session, rng):
    """An injected whole-batch OOM with adaptive OFF degrades to rung 0
    of the ladder (forced adaptive re-execution, no re-decode), where
    the skewed hash destination — thousands of DISTINCT keys that all
    hash to one device, so partial aggregation cannot collapse them —
    is fanned across replicas and re-merged. Events must show the full
    story and the result must match the no-fault run exactly."""
    from spark_tpu import faults

    spark = mesh_session
    d = 8
    cand = np.arange(60_000, dtype=np.int64)
    dest = _hash_dest(cand, d)
    hot = cand[dest == 0][:6000]
    cold = cand[dest != 0][:64]
    keys = np.concatenate([hot, cold])
    vals = rng.integers(0, 1000, keys.size)
    import pandas as pd

    spark.createDataFrame(pd.DataFrame({"k": keys, "v": vals})) \
        .createOrReplaceTempView("aqe_skew")
    q = ("SELECT k, sum(v) s, count(*) c, min(v) mn, max(v) mx "
         "FROM aqe_skew GROUP BY k ORDER BY k")
    try:
        spark.conf.set("spark.tpu.adaptive.enabled", False)
        ref = spark.sql(q).toArrow()

        spark.conf.set("spark.tpu.adaptive.skewedPartitionFactor", 2)
        spark.conf.set("spark.tpu.adaptive.skewMinRows", 256)
        spark.conf.set("spark.tpu.faultInjection.execute.device",
                       "nth:1:oom")
        faults.reset(spark.conf)
        got = spark.sql(q).toArrow()
        assert got.equals(ref)

        events = metrics.recent(8192)
        kinds = [e["kind"] for e in events]
        assert "degraded_to_adaptive" in kinds
        assert any(e["kind"] == "fault_recovered"
                   and e.get("how") == "degraded_to_adaptive"
                   for e in events)
        splits = [e for e in events if e.get("kind") == "aqe"
                  and e.get("decision") == "skew_split"]
        assert splits and 0 in splits[-1]["hot"]
        assert splits[-1]["max_incoming"] >= 6000
    finally:
        for key in _SKEW_CONF_KEYS:
            spark.conf.unset(key)
        faults.reset(spark.conf)


# ---- observability: the UI serves the exchange profile ---------------------


@pytest.mark.timeout(300)
def test_ui_exchange_endpoint(mesh_session, rng):
    import json
    import urllib.request

    from spark_tpu.ui import StatusServer

    spark = mesh_session
    import pandas as pd

    spark.createDataFrame(pd.DataFrame({
        "k": rng.integers(0, 100, 4000),
        "v": rng.integers(0, 1000, 4000),
    })).createOrReplaceTempView("aqe_ui")
    try:
        spark.conf.set("spark.tpu.adaptive.enabled", True)
        spark.sql("SELECT k, sum(v) s FROM aqe_ui GROUP BY k "
                  "ORDER BY k").collect()
    finally:
        spark.conf.unset("spark.tpu.adaptive.enabled")
    srv = StatusServer(spark, port=0)
    try:
        with urllib.request.urlopen(f"{srv.url}/api/v1/exchange",
                                    timeout=10) as r:
            payload = json.loads(r.read())
    finally:
        srv.stop()
    prof = payload["profile"]
    assert prof["exchanges"] >= 1 and prof["rows_sent"] > 0
    assert any(ex["mode"] == "adaptive" for ex in prof["by_op"].values())
    assert payload["gauges"].get("exchange.mode") == "adaptive"


# ---- measured admission: scheduler uses observed, not static, bytes --------


@pytest.mark.timeout(300)
def test_measured_bytes_feed_admission(mesh_session, rng):
    from spark_tpu.scheduler import admission

    spark = mesh_session
    import pandas as pd

    spark.createDataFrame(pd.DataFrame({
        "k": rng.integers(0, 50, 4000),
        "v": rng.integers(0, 1000, 4000),
    })).createOrReplaceTempView("aqe_adm")
    df = spark.sql("SELECT k, sum(v) s FROM aqe_adm GROUP BY k ORDER BY k")
    df.collect()
    measured = admission.measured_plan_bytes(df._plan)
    assert measured is not None and measured > 0
    est = admission.estimate_plan_bytes(df._plan, spark.conf)
    assert est == max(admission.MIN_ESTIMATE_BYTES, measured)


# ---- conf hygiene ----------------------------------------------------------


def test_all_adaptive_conf_keys_declared():
    """Every spark.tpu.adaptive.* / spark.tpu.kernels.* key referenced
    anywhere in the source is registered in conf.py with a default and
    a docstring (the declaration contract the storage suite pioneered)."""
    root = os.path.join(os.path.dirname(__file__), "..", "spark_tpu")
    used = set()
    for path in glob.glob(os.path.join(root, "**", "*.py"),
                          recursive=True):
        with open(path) as f:
            # maximal dotted match so nested namespaces
            # (spark.tpu.adaptive.agg.enabled) resolve to the full key,
            # not the unregistered spark.tpu.adaptive.agg prefix
            used.update(re.findall(
                r"spark\.tpu\.(?:adaptive|kernels)\.\w+(?:\.\w+)*",
                f.read()))
    assert used, "no adaptive/kernels conf keys found in source"
    for key in used:
        assert key in CF._REGISTRY, f"{key} not registered in conf.py"
        entry = CF._REGISTRY[key]
        assert entry.doc and len(entry.doc) > 20, f"{key} lacks a doc"
        assert entry.default is not None, f"{key} lacks a default"


def test_searchsorted_sort_threshold_conf(monkeypatch):
    """kernels.searchsorted flips scan->sort when
    v.size * threshold > a.size; the threshold must come from the
    active session conf, falling back to the declared default."""
    from spark_tpu.api.session import SparkSession

    captured = {}
    real = jnp.searchsorted

    def spy(a, v, side="left", method="scan"):
        captured["method"] = method
        return real(a, v, side=side, method=method)

    monkeypatch.setattr(K.jnp, "searchsorted", spy)
    a = jnp.arange(8192, dtype=jnp.int64)
    v = jnp.arange(4096, dtype=jnp.int64)

    prev = SparkSession._active
    SparkSession._reset()
    spark = SparkSession.builder.appName("aqe-kernels").getOrCreate()
    try:
        assert (K._searchsorted_sort_threshold()
                == CF.SEARCHSORTED_SORT_THRESHOLD.default)
        # default threshold (50): 4096 * 50 >> 8192 -> sort-based merge
        K.searchsorted(a, v)
        assert captured["method"] == "sort"
        # threshold 1: 4096 * 1 <= 8192 -> per-element binary search
        spark.conf.set("spark.tpu.kernels.searchsortedSortThreshold", 1)
        assert K._searchsorted_sort_threshold() == 1
        K.searchsorted(a, v)
        assert captured["method"] == "scan"
    finally:
        spark.conf.unset("spark.tpu.kernels.searchsortedSortThreshold")
        SparkSession._reset()
        SparkSession._active = prev

"""Test harness: a 'local-mesh' analogue of the reference's local[N] /
local-cluster[n,c,m] master URLs (reference: SparkContext master parsing;
LocalSparkCluster.scala) — 8 virtual CPU devices so distributed paths are
exercised without TPU hardware (SURVEY.md §4 'Lesson for the TPU build').

Note: the axon sitecustomize force-registers the TPU backend and
overwrites JAX_PLATFORMS, so forcing CPU must go through jax.config
AFTER import, not the environment.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: XLA CPU compiles are multi-second on this
# host; without the disk cache the TPC-H suite pays ~100 compiles/query.
from spark_tpu.api.session import _enable_compilation_cache  # noqa: E402

_enable_compilation_cache()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def spark():
    from spark_tpu.api.session import SparkSession

    return SparkSession.builder.getOrCreate()

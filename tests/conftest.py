"""Test harness: a 'local-mesh' analogue of the reference's local[N] /
local-cluster[n,c,m] master URLs (reference: SparkContext master parsing;
LocalSparkCluster.scala) — 8 virtual CPU devices so distributed paths are
exercised without TPU hardware (SURVEY.md §4 'Lesson for the TPU build').

Note: the axon sitecustomize force-registers the TPU backend and
overwrites JAX_PLATFORMS, so forcing CPU must go through jax.config
AFTER import, not the environment.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# XLA:CPU AOT executable (de)serialization aborts/segfaults
# nondeterministically deep into the full-suite process (see
# session._enable_compilation_cache); tests run without the disk cache.
os.environ.setdefault("SPARK_TPU_JAX_CACHE", "0")


def _raise_map_count_limit() -> None:
    """The full suite jit-compiles thousands of XLA programs in ONE
    process; each maps several executable/code regions, and the process
    blows through the default vm.max_map_count (65530) near the END of
    the run — mmap starts failing and XLA:CPU crashes (SIGSEGV/SIGABRT
    in compile/serialize/deserialize, diagnosed by watching
    /proc/<pid>/maps grow ~4k/min to the limit). Raise the limit when
    we can (root in CI images); otherwise leave a loud hint."""
    try:
        with open("/proc/sys/vm/max_map_count") as f:
            cur = int(f.read())
        if cur < 1 << 20:
            with open("/proc/sys/vm/max_map_count", "w") as f:
                f.write(str(1 << 21))
    except (OSError, ValueError):
        import warnings

        warnings.warn(
            "could not raise vm.max_map_count; the full suite may "
            "crash near the end when XLA mappings exhaust the limit "
            "(run: sysctl -w vm.max_map_count=2097152)")


_raise_map_count_limit()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from spark_tpu.api.session import _enable_compilation_cache  # noqa: E402

_enable_compilation_cache()  # no-op under SPARK_TPU_JAX_CACHE=0 (above)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="run tests marked slow (the FULL TPC-H both-engine sweep; "
             "the default selection keeps the suite under ~5 min while "
             "still covering every operator class)")


def pytest_configure(config):
    # registered here as well as pyproject.toml so ad-hoc invocations
    # with -p no:cacheprovider -o addopts= never warn on the marker
    config.addinivalue_line(
        "markers",
        "storage: HBM-resident columnar storage / unified memory "
        "manager tests (spark_tpu/storage/)")
    config.addinivalue_line(
        "markers",
        "aqe: adaptive query execution over the mesh — runtime "
        "shuffle stats, capacity re-planning, broadcast switching, "
        "skew splitting")
    config.addinivalue_line(
        "markers",
        "compile: AOT compilation service tests (spark_tpu/compile/) — "
        "executable store, background compile + hot-swap, pre-warm")
    config.addinivalue_line(
        "markers",
        "analysis: static plan analysis — shape/dtype/capacity oracle, "
        "recompilation hazards, transform legality, invariant + "
        "concurrency linters")
    config.addinivalue_line(
        "markers",
        "serve: scale-out serving tier (spark_tpu/serve/) — federation "
        "router, plan-keyed result cache, cross-replica shedding")
    config.addinivalue_line(
        "markers",
        "mview: incrementally-maintained materialized views "
        "(spark_tpu/mview/) — delta detection, re-merge, stream "
        "convergence, serve repopulation")
    config.addinivalue_line(
        "markers",
        "agg: runtime-adaptive aggregation — cardinality-sketched "
        "strategy switching (partial->final / bypass / hash-partial / "
        "sort / hot-key presplit), Count-Min heavy hitters, Pallas "
        "segmented reductions, byte-identity sweeps")
    config.addinivalue_line(
        "markers",
        "trace: end-to-end query tracing (spark_tpu/trace/) — "
        "hierarchical spans, cross-replica context propagation, "
        "Perfetto export, overhead guard")
    config.addinivalue_line(
        "markers",
        "chaos: seeded chaos-campaign harness (spark_tpu/chaos.py) — "
        "randomized multi-point fault schedules asserting "
        "byte-identical-or-typed-error, zero hangs, attempts within "
        "the unified retry budget")
    config.addinivalue_line(
        "markers",
        "slo: SLO-driven serving (spark_tpu/slo/) — per-plan latency "
        "prediction, EDF scheduling, reject-at-admission, predictive "
        "brownout, on/off byte-identity")
    config.addinivalue_line(
        "markers",
        "fusion: whole-query native fusion — on-device adaptive "
        "capacity decisions, single-XLA-program multi-stage spans, "
        "bucket-ladder branch selection, staged-fallback bailouts, "
        "on/off byte-identity")


def pytest_collection_modifyitems(config, items):
    # compile tests join daemon background-compile threads; every one
    # gets the SIGALRM deadlock guard so a wedged join fails instead of
    # hanging tier-1 (tests may still carry their own tighter timeout)
    for item in items:
        if ("compile" in item.keywords or "serve" in item.keywords
                or "mview" in item.keywords or "agg" in item.keywords
                or "trace" in item.keywords
                or "chaos" in item.keywords
                or "slo" in item.keywords
                or "fusion" in item.keywords) \
                and item.get_closest_marker("timeout") is None:
            item.add_marker(pytest.mark.timeout(300))
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow: run with --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    """Deadlock guard: ``@pytest.mark.timeout(S)`` fails a test after S
    seconds instead of hanging the whole tier-1 run (pytest-timeout is
    not in the image; SIGALRM interrupts even a blocking lock acquire
    on the main thread). Scheduler tests all carry it — a wedged queue
    must fail fast, not wedge CI."""
    import signal
    import threading

    marker = item.get_closest_marker("timeout")
    seconds = float(marker.args[0]) if marker and marker.args else 0.0
    if (seconds <= 0 or not hasattr(signal, "setitimer")
            or threading.current_thread() is not threading.main_thread()):
        yield
        return

    def _on_timeout(signum, frame):
        pytest.fail(f"deadlock guard: test exceeded {seconds:g}s "
                    f"(likely a wedged queue or gate)")

    prev = signal.signal(signal.SIGALRM, _on_timeout)
    signal.setitimer(signal.ITIMER_REAL, seconds)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0.0)
        signal.signal(signal.SIGALRM, prev)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def spark():
    from spark_tpu.api.session import SparkSession

    return SparkSession.builder.getOrCreate()

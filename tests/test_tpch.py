"""TPC-H end-to-end: all 22 queries, parsed from SQL, executed on both
engines, results checked against the sqlite3 external oracle.

This is the parity harness SURVEY §4 calls for (reference model:
TPCHQuerySuite.scala:26 + golden files). Scale sf=0.02 keeps the suite
fast while producing non-empty results for every query.
"""

import pytest

from spark_tpu.tpch.gen import generate_tables, register_views
from spark_tpu.tpch.oracle import assert_rows_match, load_sqlite, run_oracle
from spark_tpu.tpch.queries import QUERIES

SF = 0.02


@pytest.fixture(scope="module")
def tpch(spark):
    # seed chosen so every query returns rows at this tiny SF (q18's
    # HAVING sum(l_quantity) > 300 is the tightest: 2 qualifying orders)
    tables = generate_tables(SF, seed=99)
    register_views(spark, tables)
    conn = load_sqlite(tables)
    return spark, tables, conn


def _rows(df):
    return [tuple(r.values()) for r in
            (row.asDict() if hasattr(row, "asDict") else row
             for row in df.collect())]


ALL_QUERIES = sorted(QUERIES)

# Default (fast) selections keep the suite under ~5 minutes while still
# covering every operator class: grouped agg (1), joins+limit (3, 5),
# multi-join+expr (9), outer-join agg subquery (13), anti/semi patterns
# (16, 21, 22), quantity having (18). The FULL 22-query x both-engine
# sweep runs with --runslow (VERDICT r3 weak #4: a suite nobody can
# wait for stops being run).
FAST_SINGLE = {1, 3, 5, 13, 16, 18, 22}
FAST_MESH = {1, 5}


def _mark_slow(qnums, fast):
    return [q if q in fast
            else pytest.param(q, marks=pytest.mark.slow)
            for q in qnums]


@pytest.mark.parametrize("qnum", _mark_slow(ALL_QUERIES, FAST_SINGLE))
def test_query_parity_single_device(tpch, qnum):
    spark, _, conn = tpch
    df = spark.sql(QUERIES[qnum])
    got = [tuple(r.values()) for r in (r.asDict() for r in df.collect())]
    want = run_oracle(conn, QUERIES[qnum])
    assert want, f"q{qnum}: oracle returned no rows — bad generator seed?"
    assert_rows_match(got, want, label=f"q{qnum}")


@pytest.mark.parametrize("qnum", _mark_slow(ALL_QUERIES, FAST_MESH))
def test_query_parity_mesh(tpch, qnum):
    """Distributed runs of ALL 22 queries vs the same oracle."""
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh
    from spark_tpu.sql.parser import parse_sql

    spark, _, conn = tpch
    plan = parse_sql(QUERIES[qnum], spark.catalog)
    ex = MeshExecutor(make_mesh(8))
    batch = ex.execute_logical(plan)
    got = [tuple(d.values()) for d in batch.to_pylist()]
    want = run_oracle(conn, QUERIES[qnum])
    assert_rows_match(got, want, label=f"q{qnum}[mesh]")


def test_all_queries_parse(tpch):
    """Every query text must at least tokenize+parse (plan shape only;
    execution parity above). Uses the module fixture's views — a
    private re-registration here would CLOBBER the shared catalog and
    silently poison every later test in the module (found the hard way:
    re-execution parity compared sf0.001 results to the sf0.02
    oracle)."""
    from spark_tpu.sql.parser import parse_sql

    spark, _, _ = tpch
    for qnum, text in QUERIES.items():
        plan = parse_sql(text, spark.catalog)
        assert plan.schema.names, f"q{qnum} produced no schema"


@pytest.mark.parametrize("qnum", _mark_slow([3, 5, 7, 10, 18],
                                             {3, 5, 18}))
def test_query_parity_reexecution(tpch, qnum):
    """Second executions replay through the adaptive TRACED join paths
    (sized expansion / swapped / unique-build gather chosen by output
    capacity) — assert they produce the same oracle-checked rows as the
    first, blocking, run."""
    spark, _, conn = tpch
    df = spark.sql(QUERIES[qnum])
    first = _rows(df)
    second = _rows(df)
    want = run_oracle(conn, QUERIES[qnum])
    assert_rows_match(first, want, label=f"q{qnum}[run1]")
    assert_rows_match(second, want, label=f"q{qnum}[run2]")


@pytest.mark.parametrize("qnum", _mark_slow([1, 6, 14, 19], {6}))
def test_query_parity_parquet_scan(tpch, tmp_path, qnum):
    """Parquet-backed runs: decimal columns + predicate pushdown through
    the datasource (the in-memory fixture path skips translate_filters
    entirely, so q6-style decimal-vs-float pushed literals only get
    exercised here)."""
    from spark_tpu.tpch.gen import write_parquet

    spark, tables, conn = tpch
    path = str(tmp_path / "tpch_pq")
    write_parquet(tables, path)
    try:
        register_views(spark, path=path)
        df = spark.sql(QUERIES[qnum])
        got = _rows(df)
        want = run_oracle(conn, QUERIES[qnum])
        assert_rows_match(got, want, label=f"q{qnum}[parquet]")
    finally:
        register_views(spark, tables)  # restore in-memory views

"""Regenerate golden results: ``python -m tests.sql_golden.regen``.

sqlite-oracled files run against sqlite3 (independent implementation);
``-- oracle: engine`` files run against the engine itself (regression
locks, matching the reference's self-generated goldens)."""

from __future__ import annotations

import os
import sys

from tests.sql_golden import harness as H


def main() -> int:
    import jax

    # goldens are platform-independent; CPU avoids cold TPU compiles
    # (the axon sitecustomize overrides JAX_PLATFORMS, so use config)
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from spark_tpu.api.session import SparkSession

    spark = SparkSession.builder.getOrCreate()
    H.setup_engine(spark)
    conn = H.setup_sqlite()

    failures = 0
    for fname in H.input_files():
        oracle, stmts = H.parse_input(os.path.join(H.INPUTS, fname))
        entries = []
        for sql in stmts:
            try:
                if oracle == "engine":
                    rows = H.run_engine(spark, sql)
                else:
                    rows = H.run_sqlite(conn, sql)
                entries.append((sql, rows))
            except Exception as e:  # noqa: BLE001
                print(f"[regen] {fname}: {type(e).__name__}: {e}\n  {sql}",
                      file=sys.stderr)
                failures += 1
        out = os.path.join(H.GOLDENS, fname[:-4] + ".out")
        H.write_golden(out, entries)
        print(f"[regen] {fname}: {len(entries)} queries ({oracle})")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())

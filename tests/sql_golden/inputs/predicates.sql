-- predicate surface: between, in, like, case nesting
select a, b from t1 where b between 10 and 30 order by a nulls first, b;
select a from t1 where a in (1, 3, 5) order by a;
select a from t1 where a not in (1, 2) order by a;
select s from t1 where s like 'a%' order by s;
select s from t1 where s like '%an%' order by s;
select s from t1 where s like '_pple' order by s;
select a, case when b < 20 then 'low' when b < 45 then 'mid' else 'high' end from t1 where b is not null order by a nulls first, b;
select a, b from t1 where (a, b) in (select a, d from t2) order by a;

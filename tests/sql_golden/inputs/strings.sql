-- string function surface shared with the oracle
select s, length(s), upper(s), lower(s) from t1 order by s nulls first;
select substr(s, 1, 3) from t1 where s is not null order by s;
select substr(s, 2) from t1 where s is not null order by s;
select replace(s, 'a', 'o') from t1 where s is not null order by s;
select trim('  pad  ');
select s || '-' || t from t1 join t2 on t1.a = t2.a order by s nulls first, t;
select s from t1 where upper(s) = 'APPLE' order by s;

-- window functions: ranking, running frames, lag/lead, partitions
-- (reference input: window.sql)
select a, b, row_number() over (order by a nulls first, b nulls first) from t1 order by a nulls first, b nulls first;
select a, b, rank() over (order by b nulls first) from t1 order by a nulls first, b nulls first;
select a, b, dense_rank() over (order by b nulls first) from t1 order by a nulls first, b nulls first;
select a, b, sum(b) over (partition by a order by b nulls first rows between unbounded preceding and current row) from t1 order by a nulls first, b nulls first;
select a, b, sum(b) over (partition by a) from t1 order by a nulls first, b nulls first;
select a, b, lag(b, 1) over (order by a nulls first, b nulls first) from t1 order by a nulls first, b nulls first;
select a, b, lead(b, 1, -1) over (order by a nulls first, b nulls first) from t1 order by a nulls first, b nulls first;
select id, salary, sum(salary) over (order by salary nulls first rows between 1 preceding and 1 following) from emp order by salary nulls first, id;
select a, b, min(b) over (partition by a order by b nulls first rows between current row and unbounded following) from t1 order by a nulls first, b nulls first;

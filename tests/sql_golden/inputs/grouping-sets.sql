-- oracle: engine
-- grouping sets / rollup / cube (sqlite lacks them; regression lock,
-- reference input: grouping_set.sql, group-analytics.sql)
select a, s, count(*) from t1 group by grouping sets ((a), (s)) order by a nulls first, s nulls first;
select a, s, sum(b), grouping(a), grouping(s) from t1 group by rollup (a, s) order by a nulls first, s nulls first, 3 nulls first;
select a, s, count(*) from t1 group by cube (a, s) order by a nulls first, s nulls first, 3;

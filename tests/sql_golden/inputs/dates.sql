-- date columns order/compare/group; both engines print ISO dates
select id, hired from emp order by hired, id;
select id from emp where hired >= '2021-01-01' order by id;
select max(hired), min(hired) from emp;
select dept, max(hired) from emp group by dept order by dept nulls first;
select id, hired from emp where hired between '2020-01-01' and '2021-12-31' order by id;

-- oracle: engine
-- map construction / lookup (regression lock; types.MapType)
select map('k1', a, 'k2', b) from t1 where a is not null and b is not null order by a, b;
select element_at(map('x', 1, 'y', 2), 'y'), map('x', 1)['x'];
select map_keys(map('a', 1, 'b', 2)), map_values(map('a', 1, 'b', 2));
select map_contains_key(map('a', 1), 'a'), map_contains_key(map('a', 1), 'z');

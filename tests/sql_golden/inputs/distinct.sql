-- DISTINCT corners: nulls collapse, multi-column
select distinct s from t1 order by s nulls first;
select distinct a, b from t1 order by a nulls first, b nulls first;
select count(distinct b) from t1;
select distinct a % 2 from t1 order by 1 nulls first;
select distinct t1.a from t1 join t2 on t1.a = t2.a order by t1.a;

-- three-valued logic and null arithmetic
-- (reference inputs: null-propagation.sql, comparators.sql)
select a + b, a - b, a * b from t1 order by a nulls first, b nulls first;
select coalesce(a, b, 99), coalesce(c, -1.0) from t1 order by a nulls first, b nulls first;
select nullif(b, 10) as n1, nullif(a, a) as n2 from t1 order by a nulls first, b nulls first;
select a, b from t1 where a = 2 and b is null order by a;
select a, b from t1 where a is null or b is null order by a nulls first, b nulls first;
select count(*) from t1 where (a > 2) is null;
select a from t1 where not (a < 3) order by a;
select case when a is null then -1 else a end from t1 order by 1;

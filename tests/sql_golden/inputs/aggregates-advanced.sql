-- oracle: engine
-- stddev family, exact percentiles, collection aggregates
select round(stddev(b), 4), round(var_pop(b), 4) from t1;
select a, percentile_approx(b, 0.5), median(b) from t1 group by a order by a nulls first;
select a, collect_list(b) from t1 where b is not null group by a order by a nulls first;
select a, collect_set(s) from t1 group by a order by a nulls first;
select percentile(b, 0.25) from t1;

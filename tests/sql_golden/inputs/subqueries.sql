-- scalar / IN / EXISTS / correlated subqueries
-- (reference inputs: scalar-subquery.sql, exists-subquery in subquery/)
select a, b from t1 where b = (select max(d) from t2 where t2.a = t1.a) order by a;
select a from t1 where exists (select 1 from t2 where t2.a = t1.a) order by a;
select a from t1 where not exists (select 1 from t2 where t2.a = t1.a) order by a nulls first;
select a from t1 where a in (select a from t2) order by a;
select a from t1 where a not in (select a from t2 where a is not null) order by a;
select (select count(*) from t2), a from t1 order by a nulls first;
select a, (select sum(d) from t2 where t2.a = t1.a) from t1 order by a nulls first;

-- grouped aggregation corners: null keys group together, having,
-- expression keys, distinct aggregates (reference input: group-by.sql)
select a, count(*), count(b), sum(b), min(b), max(b) from t1 group by a order by a nulls first;
select a, avg(c) from t1 group by a having count(*) > 1 order by a nulls first;
select a % 2, sum(b) from t1 where a is not null group by a % 2 order by 1;
select count(distinct s), count(distinct a) from t1;
select s, count(distinct a) from t1 group by s order by s nulls first;
select count(*) from t1;
select sum(b), avg(b * 1.0), min(c), max(c) from t1;
select a, b, count(*) from t1 group by a, b order by a nulls first, b nulls first;

-- set-op corners: duplicates, nulls equal under set ops
-- (reference inputs: union.sql, intersect-all.sql, except.sql)
select a from t1 union select a from t2 order by a nulls first;
select a from t1 union all select a from t2 order by a nulls first;
select a from t1 intersect select a from t2 order by a nulls first;
select a from t1 except select a from t2 order by a nulls first;
select s from t1 union select t from t2 order by s nulls first;
select a, b from t1 union select a, d from t2 order by a nulls first, b nulls first;
select a from t2 except select a from t1 order by a nulls first;

-- arithmetic corners with an independent oracle: float division,
-- remainder sign, abs/round, unary minus
select a, b, a * 1.0 / b from t1 where b is not null and b != 0 order by a nulls first, b;
select b % 7, -b from t1 where b is not null order by b, b % 7;
select abs(c), round(c) from t1 where c is not null order by c;
select round(c, 1) from t1 where c is not null order by c;
select max(b) - min(b), sum(b) * 1.0 / count(b) from t1;
select a + 0.5, a - 0.5 from t1 where a is not null order by a;

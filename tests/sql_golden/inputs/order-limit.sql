-- ordering corners: explicit null placement, desc, limit/offset
select a, b from t1 order by a asc nulls first, b asc nulls first;
select a, b from t1 order by a desc nulls last, b desc nulls last;
select a, b from t1 order by a asc nulls last, b nulls first;
select b from t1 order by b nulls first limit 3;
select b from t1 order by b nulls last limit 3;
select a, b from t1 order by a nulls first, b nulls first limit 4 offset 3;
select distinct a from t1 order by a nulls first;

-- oracle: engine
-- lambdas over arrays (regression lock; reference: higherOrderFunctions)
select transform(array(a, b), x -> x * 10) from t1 where a is not null and b is not null order by a, b;
select filter(array(1, 2, 3, 4), x -> x % 2 = 0);
select exists(array(b, 10), x -> x > 35) from t1 where b is not null order by b;
select aggregate(array(a, b), 0, (acc, x) -> acc + x) from t1 where a is not null and b is not null order by a, b;
select forall(array(1, 2, 3), x -> x < 10);

-- oracle: engine
-- array construction / access / explode (regression lock)
select array(a, b) from t1 where a is not null and b is not null order by a, b;
select size(array(1, 2, 3)), element_at(array(10, 20), 2), array(5, 6)[0];
select array_contains(array(a, b), 10) from t1 where a is not null and b is not null order by a, b;
select a, x from t1 lateral view explode(array(b, b + 1)) v as x where a = 1 order by a, b, x;

-- join types incl. null keys (never match) and duplicate keys
-- (reference inputs: join-empty-relation.sql, natural-join.sql)
select t1.a, t1.b, t2.d from t1 join t2 on t1.a = t2.a order by t1.a, t1.b nulls first, t2.d;
select t1.a, t1.b, t2.d from t1 left join t2 on t1.a = t2.a order by t1.a nulls first, t1.b nulls first, t2.d nulls first;
select t1.a, t2.d from t1 right join t2 on t1.a = t2.a order by t1.a nulls first, t2.d nulls first;
select t1.a, t1.b, t2.d from t1 full outer join t2 on t1.a = t2.a order by t1.a nulls first, t1.b nulls first, t2.d nulls first;
select count(*) from t1 join t2 on t1.a = t2.a and t1.b < t2.d;
select t1.a from t1 join t2 on t1.a = t2.a where t2.t = 'y' order by t1.a;
select count(*) from t1 cross join t2;
select t1.a, t2.a from t1 join t2 on t1.a < t2.a order by t1.a, t2.a;

-- oracle: engine
-- engine date function surface (sqlite spells these differently)
select id, year(hired), month(hired), day(hired) from emp order by id;
select id, date_add(hired, 30) from emp order by id;
select dept, min(hired), max(hired) from emp group by dept order by dept nulls first;
select id from emp where year(hired) = 2021 order by id;

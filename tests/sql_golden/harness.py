"""Golden-file SQL harness (reference: SQLQueryTestSuite.scala:133 over
sql/core/src/test/resources/sql-tests/inputs/*.sql with checked-in
golden results).

Input files live in ``inputs/*.sql`` — semicolon-separated statements,
``--`` comments. A file whose FIRST line is ``-- oracle: engine`` is an
engine-regression lock (features sqlite lacks: grouping sets, arrays,
maps, higher-order functions — the reference's goldens are likewise
self-generated); every other file's goldens come from the INDEPENDENT
sqlite oracle, so dialect semantics (null ordering, three-valued logic,
set-op corners, window frames) are cross-checked against a second
implementation.

Golden format (``goldens/<name>.out``)::

    -- !query
    select ...
    -- !results
    1|NULL|x
    ...

Regenerate with ``python -m tests.sql_golden.regen`` from the repo
root. Queries must be DETERMINISTIC (ORDER BY everything or be a single
aggregate row); the harness additionally sorts rows defensively so an
ambiguous tie cannot flake.
"""

from __future__ import annotations

import datetime
import decimal
import os
import sqlite3
from typing import List, Tuple

HERE = os.path.dirname(__file__)
INPUTS = os.path.join(HERE, "inputs")
GOLDENS = os.path.join(HERE, "goldens")

# ---- shared base tables ------------------------------------------------------
#
# Small, null-riddled, duplicate-riddled tables both engines build
# identically. Dates are ISO strings in sqlite (its native convention)
# and DATE columns in the engine; both print identically.

T1_ROWS = [
    # (a, b, c, s)
    (1, 10, 1.5, "apple"),
    (1, 20, -2.25, "banana"),
    (2, 10, 0.0, "apple"),
    (2, None, 3.5, None),
    (3, 30, None, "cherry"),
    (None, 40, 7.25, "banana"),
    (None, None, None, None),
    (4, 10, 2.5, "date"),
    (4, 40, 2.5, "apple"),
    (5, 50, -1.0, "elder"),
    (2, 20, 4.75, "fig"),
    (3, 10, 1.25, "grape"),
]

T2_ROWS = [
    # (a, d, t)
    (1, 100, "x"),
    (2, 200, "y"),
    (2, 201, "y"),
    (6, 600, "z"),
    (None, 700, "w"),
    (4, None, "x"),
]

EMP_ROWS = [
    # (id, name, dept, salary, hired)
    (1, "alice", "eng", 100.0, "2020-01-15"),
    (2, "bob", "eng", 90.0, "2021-03-01"),
    (3, "carol", "sales", 80.0, "2019-07-30"),
    (4, "dan", "sales", 80.0, "2022-11-11"),
    (5, "erin", "hr", 70.0, "2020-06-01"),
    (6, "frank", None, 60.0, "2023-02-28"),
    (7, "grace", "eng", None, "2021-09-09"),
]


def setup_sqlite() -> sqlite3.Connection:
    conn = sqlite3.connect(":memory:")
    conn.execute("create table t1(a integer, b integer, c real, s text)")
    conn.executemany("insert into t1 values (?,?,?,?)", T1_ROWS)
    conn.execute("create table t2(a integer, d integer, t text)")
    conn.executemany("insert into t2 values (?,?,?)", T2_ROWS)
    conn.execute("create table emp(id integer, name text, dept text, "
                 "salary real, hired text)")
    conn.executemany("insert into emp values (?,?,?,?,?)", EMP_ROWS)
    conn.commit()
    return conn


def setup_engine(spark) -> None:
    import pyarrow as pa

    def col(rows, i, typ):
        return pa.array([r[i] for r in rows], typ)

    t1 = pa.table({"a": col(T1_ROWS, 0, pa.int64()),
                   "b": col(T1_ROWS, 1, pa.int64()),
                   "c": col(T1_ROWS, 2, pa.float64()),
                   "s": col(T1_ROWS, 3, pa.string())})
    t2 = pa.table({"a": col(T2_ROWS, 0, pa.int64()),
                   "d": col(T2_ROWS, 1, pa.int64()),
                   "t": col(T2_ROWS, 2, pa.string())})
    emp = pa.table({
        "id": col(EMP_ROWS, 0, pa.int64()),
        "name": col(EMP_ROWS, 1, pa.string()),
        "dept": col(EMP_ROWS, 2, pa.string()),
        "salary": col(EMP_ROWS, 3, pa.float64()),
        "hired": pa.array([datetime.date.fromisoformat(r[4])
                           for r in EMP_ROWS], pa.date32()),
    })
    spark.createDataFrame(t1).createOrReplaceTempView("t1")
    spark.createDataFrame(t2).createOrReplaceTempView("t2")
    spark.createDataFrame(emp).createOrReplaceTempView("emp")


# ---- normalization -----------------------------------------------------------


def norm_value(v) -> str:
    """One canonical text form both engines map onto: the golden file
    currency."""
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "1" if v else "0"  # sqlite's boolean surface
    if isinstance(v, float):
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return f"{v:.6g}"
    if isinstance(v, decimal.Decimal):
        f = float(v)
        return str(int(f)) if f == int(f) else f"{f:.6g}"
    if isinstance(v, datetime.datetime):
        return v.isoformat(sep=" ")
    if isinstance(v, datetime.date):
        return v.isoformat()
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(norm_value(x) for x in v) + "]"
    if isinstance(v, dict):
        items = sorted(v.items(), key=lambda kv: str(kv[0]))
        return "{" + ",".join(f"{norm_value(k)}:{norm_value(x)}"
                              for k, x in items) + "}"
    return str(v)


def norm_rows(rows: List[tuple]) -> List[str]:
    out = ["|".join(norm_value(v) for v in row) for row in rows]
    return sorted(out)  # defensive: ties must not flake


# ---- execution ---------------------------------------------------------------


def run_sqlite(conn: sqlite3.Connection, sql: str) -> List[str]:
    return norm_rows([tuple(r) for r in conn.execute(sql).fetchall()])


def run_engine(spark, sql: str) -> List[str]:
    rows = spark.sql(sql).collect()
    return norm_rows([tuple(r.asDict().values()) for r in rows])


# ---- file formats ------------------------------------------------------------


def parse_input(path: str) -> Tuple[str, List[str]]:
    """Returns (oracle, statements)."""
    with open(path) as f:
        text = f.read()
    oracle = "sqlite"
    lines = text.splitlines()
    if lines and lines[0].strip().lower().startswith("-- oracle:"):
        oracle = lines[0].split(":", 1)[1].strip()
    body = "\n".join(ln for ln in lines
                     if not ln.strip().startswith("--"))
    stmts = [s.strip() for s in body.split(";") if s.strip()]
    return oracle, stmts


def read_golden(path: str) -> List[Tuple[str, List[str]]]:
    out = []
    query: List[str] = []
    results: List[str] = []
    mode = None
    with open(path) as f:
        for line in f.read().splitlines():
            if line == "-- !query":
                if mode == "results":
                    out.append(("\n".join(query), results))
                query, results, mode = [], [], "query"
            elif line == "-- !results":
                mode = "results"
            elif mode == "query":
                query.append(line)
            elif mode == "results":
                results.append(line)
    if mode == "results":
        out.append(("\n".join(query), results))
    return out


def write_golden(path: str, entries: List[Tuple[str, List[str]]]) -> None:
    with open(path, "w") as f:
        for sql, rows in entries:
            f.write("-- !query\n")
            f.write(sql + "\n")
            f.write("-- !results\n")
            for r in rows:
                f.write(r + "\n")


def input_files() -> List[str]:
    return sorted(f for f in os.listdir(INPUTS) if f.endswith(".sql"))

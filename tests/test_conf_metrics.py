"""Config registry wiring + per-stage metrics/event log (reference:
SQLConf autoBroadcastJoinThreshold, SQLMetrics.scala:40,
EventLoggingListener.scala:48)."""

import json
import os

from spark_tpu import metrics
from spark_tpu.conf import RuntimeConf


def _mesh_executor(conf=None):
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh

    return MeshExecutor(make_mesh(4), conf=conf)


def _join_plan(spark):
    left = spark.createDataFrame(
        [{"k": i % 8, "v": i} for i in range(64)])
    right = spark.createDataFrame(
        [{"k": i, "w": i * 10} for i in range(8)])
    from spark_tpu.plan import logical as L
    from spark_tpu.expr import expressions as E

    return L.Join(left._plan, right._plan, "inner",
                  (E.Col("k"),), (E.Col("k"),))


def test_broadcast_threshold_zero_forces_partitioned(spark, monkeypatch):
    import spark_tpu.parallel.executor as X

    plan = _join_plan(spark)

    conf0 = RuntimeConf({"spark.sql.autoBroadcastJoinThreshold": 0})
    ex0 = _mesh_executor(conf0)
    orig_run = X.MeshExecutor.run
    exch_seen = []

    def run_spy(self, p):
        from spark_tpu.parallel import operators as D

        if isinstance(p, D.HashPartitionExchangeExec):
            exch_seen.append(True)
        return orig_run(self, p)

    monkeypatch.setattr(X.MeshExecutor, "run", run_spy)
    rows = ex0.execute_logical(plan).to_pylist()
    assert len(rows) == 64
    assert exch_seen, "threshold=0 must force a partitioned (exchange) join"

    exch_seen.clear()
    conf_big = RuntimeConf(
        {"spark.sql.autoBroadcastJoinThreshold": 1 << 30})
    ex1 = _mesh_executor(conf_big)
    rows = ex1.execute_logical(plan).to_pylist()
    assert len(rows) == 64
    assert not exch_seen, "huge threshold must broadcast the tiny build"


def test_stage_events_recorded(spark):
    metrics.reset()
    df = spark.createDataFrame([{"x": i} for i in range(10)])
    df.groupBy((df.x % 3).alias("g")).count().collect()
    evs = metrics.last_query()
    kinds = {e["kind"] for e in evs}
    assert "query_start" in kinds and "stage" in kinds, kinds
    stage = [e for e in evs if e["kind"] == "stage"]
    assert all("ms" in e for e in stage)


def test_event_log_jsonl(spark, tmp_path):
    spark.conf.set("spark.eventLog.dir", str(tmp_path))
    try:
        df = spark.createDataFrame([{"x": 1}, {"x": 2}])
        assert df.count() == 2
        path = os.path.join(str(tmp_path), "events.jsonl")
        assert os.path.exists(path)
        lines = [json.loads(ln) for ln in open(path)]
        assert any(e["kind"] == "stage" for e in lines)
    finally:
        spark.conf.unset("spark.eventLog.dir")

"""Tracing/profiling glue (spark_tpu/tracing.py; SURVEY §5)."""

import os

from spark_tpu import metrics, tracing


def test_query_profile_rolls_up_stage_events(spark):
    metrics.reset()
    spark.range(1000).filter("id % 3 = 0").count()
    prof = tracing.query_profile()
    assert prof, "no stage events recorded by the engine"
    assert all({"count", "total_ms", "max_ms"} <= set(v)
               for v in prof.values())
    text = tracing.format_profile(prof)
    assert "operator" in text and "total_ms" in text


def test_planning_tracker():
    t = tracing.PlanningTracker()
    with t.phase("parse"):
        pass
    with t.phase("optimize"):
        sum(range(1000))
    with t.phase("optimize"):
        pass
    ph = t.phases()
    assert set(ph) == {"parse", "optimize"} and ph["optimize"] >= 0


def test_jax_profiler_trace_writes_files(tmp_path, spark):
    d = str(tmp_path / "trace")
    with tracing.trace(d):
        with tracing.annotate("q1"):
            spark.range(100).count()
    found = []
    for root, _, files in os.walk(d):
        found.extend(files)
    assert found, "jax profiler produced no trace files"

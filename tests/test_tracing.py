"""Tracing/profiling glue (spark_tpu/tracing.py; SURVEY §5)."""

import os

from spark_tpu import metrics, tracing


def test_query_profile_rolls_up_stage_events(spark):
    metrics.reset()
    spark.range(1000).filter("id % 3 = 0").count()
    prof = tracing.query_profile()
    assert prof, "no stage events recorded by the engine"
    assert all({"count", "total_ms", "max_ms"} <= set(v)
               for v in prof.values())
    text = tracing.format_profile(prof)
    assert "operator" in text and "total_ms" in text


def test_planning_tracker():
    t = tracing.PlanningTracker()
    with t.phase("parse"):
        pass
    with t.phase("optimize"):
        sum(range(1000))
    with t.phase("optimize"):
        pass
    ph = t.phases()
    assert set(ph) == {"parse", "optimize"} and ph["optimize"] >= 0


def test_jax_profiler_trace_writes_files(tmp_path, spark):
    d = str(tmp_path / "trace")
    with tracing.trace(d):
        with tracing.annotate("q1"):
            spark.range(100).count()
    found = []
    for root, _, files in os.walk(d):
        found.extend(files)
    assert found, "jax profiler produced no trace files"


def test_pipeline_profile_rolls_up_chunk_events():
    evs = [
        {"kind": "chunked_agg", "chunks": 4, "decode_ms": 10.0,
         "transfer_ms": 5.0, "compute_ms": 8.0, "wall_ms": 20.0,
         "overlap_ms": 5.0, "pipeline_depth": 2},
        {"kind": "chunked_agg", "chunks": 2, "decode_ms": 4.0,
         "transfer_ms": 1.0, "compute_ms": 2.0, "wall_ms": 10.0,
         "overlap_ms": 1.0, "pipeline_depth": 2},
        {"kind": "stage", "op": "HashAggregate", "ms": 3.0},
    ]
    prof = tracing.pipeline_profile(evs)
    assert set(prof) == {"chunked_agg"}
    rec = prof["chunked_agg"]
    assert rec["chunks"] == 6
    assert rec["decode_ms"] == 14.0
    assert rec["overlap_ms"] == 6.0
    assert rec["overlap_ratio"] == 0.2  # 6 / 30
    text = tracing.format_pipeline_profile(prof)
    assert "chunked_agg" in text and "overlap" in text

    assert tracing.pipeline_profile([]) == {}
    assert "no out-of-HBM" in tracing.format_pipeline_profile({})

"""Fleet data plane (spark_tpu/serve/ownership.py): replica-owned
shards with epoch-fenced ownership failover and coherent fleet-wide
caches.

Covers the ownership map (rendezvous hashing: deterministic, minimal
movement on member death), shard keys (path-set only — an append must
NOT move ownership), epoch fencing (stale dispatch -> typed 409
EPOCH_RETRY absorbed by the retry budget), owner routing + byte-
identical failover, the versioned invalidation log (append / replay /
resync / subscriber push), the probe-vs-dispatch breaker race fix
(a dispatch failure trips the breaker immediately, even inside the
healthProbeSeconds throttle window), Client.last_query fleet metadata,
and the seeded concurrent append+read interleaving: once a refresh
commits, a replica that never touched the source never again returns
pre-append bytes.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_tpu import chaos, conf as CF, faults, locks, metrics, trace
from spark_tpu.conf import RuntimeConf
from spark_tpu.connect.server import Client
from spark_tpu.serve.federation import Federation
from spark_tpu.serve.ownership import (EPOCH_HEADER, EpochRetry,
                                       InvalidationLog,
                                       OwnershipCoordinator,
                                       rendezvous_owner, shard_key,
                                       session_invalidation_log)
from spark_tpu.serve.result_cache import ResultCache
from spark_tpu.serve.router import serve_fleet

pytestmark = [pytest.mark.serve, pytest.mark.timeout(240)]

_FLEET_CONF = (
    "spark.tpu.serve.ownership.enabled",
    "spark.tpu.serve.resultCache.enabled",
    "spark.tpu.serve.fingerprintCacheSeconds",
    "spark.tpu.serve.healthProbeSeconds",
    "spark.tpu.mview.enabled",
)


@pytest.fixture
def fleet3(spark, tmp_path):
    """Three-replica ownership fleet over one parquet table
    ``fleet_t``; cleans every fleet conf override on exit."""
    path = str(tmp_path / "fleet_t.parquet")
    pq.write_table(pa.table({
        "a": list(range(64)),
        "b": [float(i) * 0.5 for i in range(64)]}), path)
    spark.read.parquet(path).createOrReplaceTempView("fleet_t")
    spark.conf.set("spark.tpu.serve.ownership.enabled", "true")
    spark.conf.set("spark.tpu.serve.resultCache.enabled", "true")
    fl = serve_fleet(spark, replicas=3)
    try:
        yield fl, path
    finally:
        fl.stop()
        for k in _FLEET_CONF:
            if k in spark.conf._overrides:
                spark.conf.unset(k)
        faults.reset(spark.conf)
        log = getattr(spark, "serve_invalidation_log", None)
        if log is not None:
            for s in fl.replicas:
                if s.result_cache is not None:
                    s.result_cache.detach_invalidation_log()
        rc = getattr(spark, "serve_result_cache", None)
        if rc is not None:
            rc.clear()


# ---- ownership map: rendezvous hashing + shard keys -------------------------


def test_rendezvous_owner_deterministic_minimal_movement():
    members = ["r0", "r1", "r2", "r3"]
    shards = [f"shard-{i:03d}" for i in range(200)]
    before = {s: rendezvous_owner(s, members) for s in shards}
    # memoryless: owner depends on (shard, member set), not call order
    assert before == {
        s: rendezvous_owner(s, list(reversed(members))) for s in shards}
    # every member owns something at this shard count
    assert set(before.values()) == set(members)
    # kill r1: ONLY r1's shards move (the HRW minimal-movement
    # property the failover story depends on)
    survivors = [m for m in members if m != "r1"]
    after = {s: rendezvous_owner(s, survivors) for s in shards}
    moved = {s for s in shards if before[s] != after[s]}
    assert moved == {s for s in shards if before[s] == "r1"}
    assert all(after[s] in survivors for s in shards)


def test_shard_key_is_path_set_only(tmp_path):
    p1, p2 = str(tmp_path / "x.parquet"), str(tmp_path / "y.parquet")
    k = shard_key([p1, p2])
    assert k == shard_key([p2, p1])          # order-free
    assert k == shard_key([p1, p2, p1])      # duplicate-free
    assert k != shard_key([p1])
    # mtime-free by construction: an append (same path set) must not
    # move ownership, only invalidate caches
    pq.write_table(pa.table({"a": [1]}), p1)
    k2 = shard_key([p1, p2])
    pq.write_table(pa.table({"a": [1, 2]}), p1)
    assert shard_key([p1, p2]) == k2 == k


def test_ownership_coordinator_epoch_lifecycle():
    conf = RuntimeConf({"spark.tpu.serve.ownership.enabled": True})
    own = OwnershipCoordinator(conf)
    assert own.enabled()
    sk = shard_key(["/data/t.parquet"])
    own.register_shards({
        "t": {"shard": sk, "paths": ["/data/t.parquet"]},
        "u": {"shard": shard_key(["/data/u.parquet"]),
              "paths": ["/data/u.parquet"]}})
    minted = own.observe(["r0", "r1", "r2"])
    assert minted is not None and minted["epoch"] == own.epoch == 1
    # stable membership: no re-mint
    assert own.observe(["r2", "r1", "r0"]) is None
    # member death mints the next epoch
    minted2 = own.observe(["r0", "r2"])
    assert minted2 is not None and own.epoch == 2
    assert all(o in ("r0", "r2") for o in minted2["owners"].values())
    # table extraction routes a query to its shard's owner
    shards = own.shards_for_sql("SELECT a FROM t JOIN u ON t.a = u.a")
    assert sk in shards
    assert own.owner_for([sk]) == rendezvous_owner(sk, ["r0", "r2"])
    # epochs are monotonic — bump_to never regresses
    own.bump_to(7)
    own.bump_to(3)
    assert own.epoch == 7


def test_epoch_retry_is_typed():
    err = EpochRetry(2, 5)
    assert "EPOCH_RETRY" in str(err)
    assert err.request_epoch == 2 and err.fleet_epoch == 5
    assert chaos.is_typed_error(err)


# ---- versioned invalidation log ---------------------------------------------


def test_invalidation_log_append_since_resync():
    log = InvalidationLog(RuntimeConf(
        {"spark.tpu.serve.invalidationLog.maxRecords": 4}))
    seen, bad_calls = [], []

    def bad(_record):
        bad_calls.append(1)
        raise RuntimeError("broken subscriber")

    log.subscribe(bad)          # must not lose records for `seen`
    log.subscribe(seen.append)
    for i in range(3):
        log.append("mview_refresh", [f"/d/f{i}.parquet"])
    assert log.version == 3 and len(seen) == 3 and len(bad_calls) == 3
    assert seen[-1]["v"] == 3 and seen[-1]["kind"] == "mview_refresh"
    records, resync = log.since(1)
    assert not resync and [r["v"] for r in records] == [2, 3]
    # overflow the 4-record ring: old watermarks now need a resync
    for i in range(6):
        log.append("source_changed", [f"/d/g{i}.parquet"])
    _, resync = log.since(1)
    assert resync
    records, resync = log.since(log.version - 1)
    assert not resync and len(records) == 1
    log.unsubscribe(seen.append)
    log.append("source_changed", ["/d/zz.parquet"])
    assert seen[-1]["v"] != log.version


def test_invalidation_drops_results_and_fp_probes(spark, tmp_path):
    """The coherence core: an invalidation record drops both the
    cached result bytes AND the TTL'd fingerprint probe that would
    re-key the stale entry back to life."""
    path = str(tmp_path / "inv_t.parquet")
    pq.write_table(pa.table({"a": [1, 2, 3]}), path)
    spark.read.parquet(path).createOrReplaceTempView("inv_t")
    spark.conf.set("spark.tpu.serve.fingerprintCacheSeconds", "300.0")
    log = InvalidationLog(spark.conf)
    cache = ResultCache(spark.conf).attach_invalidation_log(log)
    try:
        df = spark.sql("SELECT a FROM inv_t WHERE a >= 2")
        key = cache.result_key(df._plan)
        cache.put(key, b"stale-bytes")
        assert cache.lookup(key) == b"stale-bytes"
        assert len(cache._fp_cache) == 1
        v = log.append("source_changed", [path])
        assert cache.invalidation_watermark == v == 1
        assert cache.lookup(key) is None
        assert len(cache._fp_cache) == 0
        # unrelated paths leave the cache alone
        cache.put(key, b"again")
        log.append("source_changed", [str(tmp_path / "other.parquet")])
        assert cache.lookup(key) == b"again"
    finally:
        cache.detach_invalidation_log()
        spark.conf.unset("spark.tpu.serve.fingerprintCacheSeconds")


def test_invalidation_fault_degrades_to_full_clear(spark, tmp_path):
    """An injected serve.invalidate fault may not leave a stale entry:
    the apply path degrades to a FULL clear (empty is always sound)."""
    log = InvalidationLog(spark.conf)
    cache = ResultCache(spark.conf).attach_invalidation_log(log)
    try:
        cache.put(("k", ()), b"v")
        spark.conf.set(
            "spark.tpu.faultInjection.serve.invalidate", "nth:1")
        faults.reset(spark.conf)
        v = log.append("source_changed", ["/nowhere/at/all.parquet"])
        assert len(cache._lru) == 0          # cleared, not stale
        assert cache.invalidation_watermark == v
    finally:
        cache.detach_invalidation_log()
        spark.conf.unset("spark.tpu.faultInjection.serve.invalidate")
        faults.reset(spark.conf)


# ---- epoch fencing + owner routing + failover -------------------------------


def _probe(fl):
    fl.router.federation.probe(force=True)


def test_epoch_fence_stale_dispatch_409(spark, fleet3):
    fl, _ = fleet3
    _probe(fl)  # discover shards, mint epoch 1, broadcast to replicas
    fed = fl.router.federation
    assert fed.ownership.epoch >= 1
    target = next(s for s in fl.replicas
                  if s.fleet_epoch == fed.ownership.epoch)
    req = urllib.request.Request(
        target.url + "/sql",
        data=json.dumps({"query": "SELECT a FROM fleet_t"}).encode(),
        headers={"Content-Type": "application/json", EPOCH_HEADER: "0"},
        method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10.0)
    assert ei.value.code == 409
    detail = json.loads(ei.value.read())
    assert detail["error"] == "EpochRetry"
    assert "EPOCH_RETRY" in detail["message"]
    assert detail["epoch"] == target.fleet_epoch
    # the fence is observable in metrics
    assert metrics.serve_stats().get("epoch_fences", 0) >= 1


def test_owner_failover_byte_identical(spark, fleet3):
    fl, _ = fleet3
    _probe(fl)
    c1 = Client(fl.url, timeout=20.0, retries=3)
    t1 = c1.sql("SELECT a, b FROM fleet_t WHERE a >= 8")
    owner = c1.last_query["replica"]
    epoch0 = fl.router.federation.ownership.epoch
    assert epoch0 >= 1
    # owner routing is sticky: the same plan lands on the same owner
    c2 = Client(fl.url, timeout=20.0, retries=3)
    t2 = c2.sql("SELECT a, b FROM fleet_t WHERE a >= 8")
    assert c2.last_query["replica"] == owner
    assert t2.equals(t1)
    # kill the owner mid-fleet: the next dispatch fails over to a
    # survivor, byte-identical, under a freshly minted epoch
    victim = next(s for s in fl.replicas if s.replica_id == owner)
    victim.stop()
    c3 = Client(fl.url, timeout=30.0, retries=3)
    t3 = c3.sql("SELECT a, b FROM fleet_t WHERE a >= 8")
    assert t3.equals(t1), "failover changed result bytes"
    assert c3.last_query["replica"] != owner
    assert fl.router.federation.ownership.epoch > epoch0


def test_client_last_query_surfaces_fleet_metadata(spark, fleet3):
    fl, _ = fleet3
    _probe(fl)
    c = Client(fl.url, timeout=20.0, retries=3)
    c.sql("SELECT a FROM fleet_t WHERE a < 4")
    lq = c.last_query
    assert lq["replica"] in {s.replica_id for s in fl.replicas}
    assert lq["cache"] in ("hit", "miss")
    assert isinstance(lq["epoch"], int) and lq["epoch"] >= 1
    # same plan again: a hit, same owner, same epoch
    c.sql("SELECT a FROM fleet_t WHERE a < 4")
    assert c.last_query["cache"] == "hit"
    assert c.last_query["replica"] == lq["replica"]


# ---- the probe-vs-dispatch breaker race (PR 14 chaos regression) ------------


def test_dispatch_failure_trips_breaker_inside_probe_throttle():
    """Regression: a replica death seen by a DISPATCH must open the
    breaker immediately, even when the probe loop is throttled by
    healthProbeSeconds and the windowed failure-rate gate (minRequests)
    has not seen enough traffic. Death is a fact, not a rate."""
    from spark_tpu.serve.federation import NoHealthyReplica

    fed = Federation(
        [("dead", "http://127.0.0.1:9")],
        conf=RuntimeConf({"spark.tpu.serve.healthProbeSeconds": 3600.0}))
    dead = fed.replicas[0]
    dead.healthy = True
    dead.last_probe = time.time()  # probe ran "just now": throttled
    assert dead.breaker.state == "closed"
    with pytest.raises(NoHealthyReplica):
        fed.dispatch(
            "POST", "/sql", json.dumps({"query": "SELECT 1"}).encode(),
            headers={"Content-Type": "application/json"})
    # ONE failed dispatch — far below the windowed minRequests gate —
    # and the breaker is already open
    assert dead.breaker.state == "open"


def test_breaker_trip_is_immediate_and_idempotent():
    fed = Federation([("x", "http://127.0.0.1:9")], conf=RuntimeConf())
    br = fed.replicas[0].breaker
    assert br.state == "closed"
    br.trip()
    assert br.state == "open"
    br.trip()  # idempotent
    assert br.state == "open"


# ---- satellite: seeded concurrent append+read interleaving ------------------


def test_concurrent_append_read_no_stale_after_refresh(
        spark, tmp_path, rng):
    """Seeded interleaving: readers hammer a SECOND replica (one that
    never appends) while the source grows and a cached-mview refresh
    commits on the first session. Reads racing the append may see old
    OR new bytes — but once the refresh commit's invalidation
    broadcast lands, no read may return pre-append bytes again."""
    path = str(tmp_path / "ivt.parquet")
    pq.write_table(pa.table({
        "a": list(range(32)),
        "b": [float(i) for i in range(32)]}), path)
    spark.read.parquet(path).createOrReplaceTempView("ivt")
    spark.conf.set("spark.tpu.serve.ownership.enabled", "true")
    spark.conf.set("spark.tpu.serve.resultCache.enabled", "true")
    spark.conf.set("spark.tpu.serve.fingerprintCacheSeconds", "300.0")
    spark.conf.set("spark.tpu.mview.enabled", "true")
    # an AGGREGATE plan: only those register as materialized views,
    # and only the mview refresh closes the plain-cache staleness hole
    q = "SELECT a % 4 AS g, SUM(b) AS s FROM ivt GROUP BY a % 4"
    cached = spark.sql(q)
    cached.cache()  # registers the mview whose refresh broadcasts
    cached.collect()
    assert len(spark.mview_manager.views()) == 1
    fl = serve_fleet(spark, replicas=2)
    try:
        fl.router.federation.probe(force=True)
        clients = {s.replica_id: Client(s.url, timeout=20.0, retries=3)
                   for s in fl.replicas}
        # warm BOTH replica caches directly (bypassing owner routing)
        pre = {rid: c.sql(q).to_pydict()
               for rid, c in clients.items()}
        assert len({json.dumps(p, sort_keys=True)
                    for p in pre.values()}) == 1
        reads, stop = [], threading.Event()
        second = sorted(clients)[1]

        def reader():
            c = clients[second]
            while not stop.is_set():
                t0 = time.time()
                reads.append((t0, c.sql(q).to_pydict()))
                time.sleep(float(rng.uniform(0.001, 0.01)))

        th = threading.Thread(target=reader, daemon=True)
        th.start()
        time.sleep(float(rng.uniform(0.01, 0.05)))  # seeded overlap
        pq.write_table(pa.table({
            "a": list(range(32)) + [100 + i for i in range(8)],
            "b": [float(i) for i in range(40)]}), path)
        # the refresh commits HERE: the local collect detects the
        # rewrite, refreshes the cached view, and broadcasts
        fresh = spark.sql(q).collect()
        commit_t = time.time()
        assert spark.mview_manager.views()[0]["refreshes"] >= 1
        deadline = time.time() + 10.0
        while time.time() < deadline:
            if any(t0 > commit_t for t0, _ in reads):
                break
            time.sleep(0.01)
        stop.set()
        th.join(timeout=20.0)
        post = [r for t0, r in reads if t0 > commit_t]
        assert post, "no read landed after the refresh commit"
        stale = [r for r in post if r == pre[second]]
        assert not stale, (
            f"{len(stale)}/{len(post)} post-commit reads returned "
            "pre-append bytes")
        # the fresh replica bytes agree with the local refresh result
        want = {row["g"]: row["s"] for row in fresh}
        got = dict(zip(post[-1]["g"], post[-1]["s"]))
        assert got == want
        cached.unpersist()
    finally:
        stop.set()
        fl.stop()
        for k in _FLEET_CONF:
            if k in spark.conf._overrides:
                spark.conf.unset(k)


# ---- registry wiring --------------------------------------------------------


def test_fleet_registrations():
    for key in ("spark.tpu.serve.ownership.enabled",
                "spark.tpu.serve.ownership.rebuildOnFailover",
                "spark.tpu.serve.ownership.rebuildTimeoutSeconds",
                "spark.tpu.serve.invalidationLog.maxRecords",
                "spark.tpu.serve.fingerprintCacheSeconds"):
        assert CF.is_registered(key), key
    assert "serve.ownership" in faults.POINTS
    assert "serve.invalidate" in faults.POINTS
    assert "serve.epoch" in trace.SPAN_NAMES
    assert "serve.invalidate" in trace.SPAN_NAMES
    assert locks.LOCK_RANKS["serve.ownership"] > \
        locks.LOCK_RANKS["serve.invalidation"]
    for m in ("epoch_mints", "epoch_retries", "epoch_fences",
              "invalidations", "rebuilds"):
        assert m in metrics.serve_stats(), m

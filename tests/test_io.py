"""Datasource layer: Parquet/CSV/JSON round-trips, projection and
predicate pushdown, partitioned writes, save modes.

Reference peers: DataSourceScanExec.scala:506 (FileSourceScanExec),
FileFormatWriter.scala:1, PartitioningUtils.scala (hive partitions),
DataFrameReader/Writer.scala.
"""

import datetime
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_tpu.api import functions as F
from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L
from spark_tpu.plan.optimizer import optimize


@pytest.fixture()
def sample_table(rng):
    n = 1000
    return pa.table({
        "id": pa.array(np.arange(n), pa.int64()),
        "grp": pa.array(rng.integers(0, 5, n), pa.int32()),
        "val": pa.array(rng.normal(size=n)),
        "name": pa.array(np.array(["aa", "bb", "cc", "dd"])[
            rng.integers(0, 4, n)]),
        "day": pa.array([datetime.date(2024, 1, 1)
                         + datetime.timedelta(days=int(d))
                         for d in rng.integers(0, 60, n)]),
    })


def test_parquet_roundtrip(spark, sample_table, tmp_path):
    p = str(tmp_path / "t.parquet")
    pq.write_table(sample_table, p)
    df = spark.read.parquet(p)
    assert df.count() == sample_table.num_rows
    got = df.toPandas().sort_values("id").reset_index(drop=True)
    want = sample_table.to_pandas().sort_values("id").reset_index(drop=True)
    pd.testing.assert_series_equal(got["val"], want["val"])
    assert list(got["name"]) == list(want["name"])
    assert list(got["day"]) == list(want["day"])


def test_write_parquet_read_back(spark, sample_table, tmp_path):
    df = spark.createDataFrame(sample_table)
    out = str(tmp_path / "out")
    df.write.parquet(out)
    assert os.path.isdir(out)
    back = spark.read.parquet(out)
    assert back.count() == sample_table.num_rows
    assert sorted(back.columns) == sorted(df.columns)
    # mode=error raises on existing path; overwrite succeeds
    with pytest.raises(FileExistsError):
        df.write.parquet(out)
    df.limit(10).write.mode("overwrite").parquet(out)
    assert spark.read.parquet(out).count() == 10


def test_partitioned_write_and_partition_pruning(spark, sample_table,
                                                 tmp_path):
    df = spark.createDataFrame(sample_table)
    out = str(tmp_path / "bygrp")
    df.write.partitionBy("grp").parquet(out)
    # hive layout on disk
    assert any(d.startswith("grp=") for d in os.listdir(out))
    back = spark.read.parquet(out)
    only2 = back.filter(E.Col("grp") == 2)
    want = sample_table.to_pandas()
    assert only2.count() == int((want["grp"] == 2).sum())
    # partition pruning: the pushed filter reaches the scan node
    plan = optimize(only2._plan)
    scan = plan
    while not isinstance(scan, L.UnresolvedScan):
        scan = scan.children()[0]
    assert scan.filters, "partition predicate was not pushed into the scan"


def test_projection_and_predicate_pushdown(spark, sample_table, tmp_path):
    p = str(tmp_path / "t2.parquet")
    pq.write_table(sample_table, p)
    df = spark.read.parquet(p).filter(E.Col("id") >= 900) \
        .select(E.Col("id"), E.Col("val"))
    plan = optimize(df._plan)
    scan = plan
    while not isinstance(scan, L.UnresolvedScan):
        scan = scan.children()[0]
    assert scan.columns is not None and set(scan.columns) == {"id", "val"}
    assert len(scan.filters) == 1
    got = df.toPandas()
    assert len(got) == 100 and got["id"].min() == 900


def test_residual_filter_stays(spark, sample_table, tmp_path):
    """Untranslatable conjuncts (arithmetic on columns) must stay in the
    plan while translatable ones push down."""
    p = str(tmp_path / "t3.parquet")
    pq.write_table(sample_table, p)
    df = spark.read.parquet(p).filter(
        (E.Col("id") >= 500) & (E.Col("id") % 7 == 0))
    plan = optimize(df._plan)
    found_filter = False
    node = plan
    while True:
        if isinstance(node, L.Filter):
            found_filter = True
        if not node.children():
            break
        node = node.children()[0]
    assert isinstance(node, L.UnresolvedScan) and node.filters
    assert found_filter, "residual conjunct was dropped"
    want = [i for i in range(500, 1000) if i % 7 == 0]
    got = sorted(r["id"] for r in df.select(E.Col("id")).collect())
    assert got == want


def test_csv_roundtrip(spark, tmp_path):
    df = spark.createDataFrame(pa.table({
        "a": pa.array([1, 2, 3], pa.int64()),
        "b": pa.array(["x", "y", "z"]),
        "c": pa.array([1.5, -2.0, 0.25]),
    }))
    out = str(tmp_path / "c")
    df.write.csv(out)
    back = spark.read.csv(out)
    got = back.toPandas().sort_values("a").reset_index(drop=True)
    assert list(got["a"]) == [1, 2, 3]
    assert list(got["b"]) == ["x", "y", "z"]
    assert list(got["c"]) == [1.5, -2.0, 0.25]


def test_csv_explicit_schema(spark, tmp_path):
    p = str(tmp_path / "raw.csv")
    with open(p, "w") as f:
        f.write("a,b\n1,2.5\n3,4.5\n")
    df = spark.read.csv(p, schema="a BIGINT, b DOUBLE")
    assert [f.dtype for f in df.schema] == \
        [__import__("spark_tpu.types", fromlist=["INT64"]).INT64,
         __import__("spark_tpu.types", fromlist=["FLOAT64"]).FLOAT64]
    assert df.count() == 2


def test_json_roundtrip(spark, tmp_path):
    df = spark.createDataFrame(pa.table({
        "a": pa.array([10, 20], pa.int64()),
        "s": pa.array(["hello", "world"]),
    }))
    out = str(tmp_path / "j")
    df.write.json(out)
    back = spark.read.json(out)
    got = back.toPandas().sort_values("a").reset_index(drop=True)
    assert list(got["a"]) == [10, 20]
    assert list(got["s"]) == ["hello", "world"]


def test_multifile_scan(spark, sample_table, tmp_path):
    d = tmp_path / "many"
    d.mkdir()
    t = sample_table.to_pandas()
    for i in range(4):
        pq.write_table(pa.Table.from_pandas(t.iloc[i * 250:(i + 1) * 250]),
                       str(d / f"part{i}.parquet"))
    df = spark.read.parquet(str(d))
    assert df.count() == 1000
    s = df.agg(F.sum("id").alias("s")).collect()[0]["s"]
    assert s == 999 * 1000 // 2


def test_mesh_reads_files(sample_table, tmp_path):
    """The mesh executor scans files too (shards after host decode)."""
    import pyarrow.parquet as pq

    from spark_tpu.api.session import SparkSession
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh

    p = str(tmp_path / "m.parquet")
    pq.write_table(sample_table, p)
    spark = SparkSession.builder.getOrCreate()
    df = spark.read.parquet(p).filter(E.Col("grp") == 1) \
        .groupBy(E.Col("name")).agg(F.count("*").alias("n"))
    ex = MeshExecutor(make_mesh(8))
    got = {r["name"]: r["n"]
           for r in ex.execute_logical(optimize(df._plan)).to_pylist()}
    want = sample_table.to_pandas()
    want = want[want["grp"] == 1].groupby("name").size().to_dict()
    assert got == want


def test_reread_after_rewrite_not_stale(spark, tmp_path):
    """Round-2 advisor finding: a FileSource must not serve cached
    batches after the underlying files were rewritten (freshness token
    in io/datasource.py:_fingerprint)."""
    import time

    path = str(tmp_path / "t")
    spark.range(5).write.parquet(path)
    df = spark.read.parquet(path)
    assert df.count() == 5
    time.sleep(0.01)  # ensure mtime_ns moves even on coarse clocks
    spark.range(9).write.mode("overwrite").parquet(path)
    assert df.count() == 9
    assert spark.read.parquet(path).count() == 9


def test_orc_roundtrip(spark, tmp_path):
    """ORC read+write through pyarrow's C++ ORC decoder (reference:
    OrcColumnarBatchReader / datasources.orc)."""
    path = str(tmp_path / "orc_t")
    spark.range(20).withColumnRenamed("id", "n").write.orc(path)
    back = spark.read.orc(path)
    assert back.count() == 20
    assert sorted(r["n"] for r in back.collect()) == list(range(20))
    # pushdown still applies
    assert back.filter("n >= 15").count() == 5


def test_duplicate_dictionary_values_collapse(spark):
    """Pre-encoded dictionary arrays may legally carry duplicate values;
    the ingest must collapse equal strings to ONE code or GROUP BY /
    DISTINCT split groups (code equality must imply value equality)."""
    import pyarrow as pa

    arr = pa.DictionaryArray.from_arrays(
        pa.array([0, 1, 2, 3], pa.int32()),
        pa.array(["x", "y", "x", "y"]))  # dup values, distinct codes
    df = spark.createDataFrame(pa.table({"s": arr}))
    got = sorted((r["s"], r["count"]) for r in
                 df.groupBy("s").count().collect())
    assert got == [("x", 2), ("y", 2)]
    assert df.select("s").distinct().count() == 2

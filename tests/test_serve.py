"""Scale-out serving tier (spark_tpu/serve/): federation router,
plan-keyed result cache with single-flight, cross-replica admission
shedding, and replica-death re-dispatch.

Every test carries the ``timeout`` deadlock guard (serve tests spin
real HTTP servers and client threads — a wedged flight must fail fast,
never hang tier-1).
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_tpu import conf as CF
from spark_tpu import faults, metrics, tracing
from spark_tpu.conf import RuntimeConf
from spark_tpu.connect.server import Client, ConnectServer
from spark_tpu.scheduler import QueryScheduler
from spark_tpu.serve import (Federation, FederationRouter, ResultCache,
                             ipc_to_table, plan_result_key, serve_fleet)
from spark_tpu.serve.result_cache import key_digest
from spark_tpu.storage.lru import LruDict

pytestmark = [pytest.mark.serve, pytest.mark.timeout(120)]


@pytest.fixture
def serve_conf(spark):
    """Serve-tier conf sandbox over the shared session: every
    spark.tpu.serve.* / serve-fault override set inside the test is
    unset afterwards and the shared result cache is dropped."""
    yield spark.conf
    for k in list(spark.conf._overrides):
        if k.startswith("spark.tpu.serve") \
                or k == "spark.tpu.faultInjection.serve.dispatch":
            spark.conf.unset(k)
    rc = getattr(spark, "serve_result_cache", None)
    if rc is not None:
        rc.clear()
    faults.reset(spark.conf)
    metrics.reset_serve()


def _write_parquet(path, nrows=64, offset=0):
    t = pa.table({
        "a": list(range(offset, offset + nrows)),
        "b": [float(i) * 0.5 for i in range(nrows)]})
    pq.write_table(t, path)
    return path


def _post_sql(url, query, headers=None, timeout=60):
    """Raw POST /sql so tests can see status code + response headers
    (X-Cache, X-SparkTpu-Replica, Retry-After) the Client hides."""
    req = urllib.request.Request(
        url + "/sql", data=json.dumps({"query": query}).encode(),
        headers={"Content-Type": "application/json",
                 **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


# ---- registration / satellites ---------------------------------------------


def test_serve_conf_keys_and_fault_point_registered():
    for key in ("spark.tpu.serve.policy",
                "spark.tpu.serve.resultCache.enabled",
                "spark.tpu.serve.resultCache.maxBytes",
                "spark.tpu.serve.dispatchRetries",
                "spark.tpu.serve.healthProbeSeconds",
                "spark.tpu.serve.replicas",
                "spark.tpu.faultInjection.serve.dispatch"):
        assert CF.is_registered(key), key
    assert "serve.dispatch" in faults.POINTS


def test_deadlock_guard_marker_registered(request):
    assert request.node.get_closest_marker("timeout") is not None


def test_scheduler_load_snapshot():
    """queue_depth()/running_count() report live load under the lock —
    the signal /health exports and least_queued routes by."""
    sched = QueryScheduler(conf=RuntimeConf({
        "spark.tpu.scheduler.maxConcurrency": 1,
        "spark.tpu.scheduler.queueDepth": 8}))
    release = threading.Event()
    started = threading.Event()

    def blocking(tk):
        started.set()
        release.wait(timeout=30)
        return 1

    try:
        assert sched.queue_depth() == 0
        assert sched.running_count() == 0
        t1 = sched.submit(blocking, description="hold")
        assert started.wait(timeout=30)
        assert sched.running_count() >= 1
        t2 = sched.submit(lambda tk: 2, description="queued")
        # one worker is held: the second submit stays in the queue
        assert sched.queue_depth() >= 1
        release.set()
        assert t1.result(timeout=30) == 1
        assert t2.result(timeout=30) == 2
        assert sched.queue_depth() == 0
        assert sched.running_count() == 0
    finally:
        release.set()
        sched.stop()


def test_client_backoff_full_jitter():
    c = Client("http://127.0.0.1:1", retries=3, backoff_s=0.05,
               max_backoff_s=0.4)
    draws = [c._jitter(2) for _ in range(64)]
    cap = min(0.4, 0.05 * 4)
    assert all(0.0 <= d <= cap for d in draws)
    # full jitter means spread, not a deterministic delay: a herd of
    # rejected clients must not re-arrive in lockstep
    assert len({round(d, 6) for d in draws}) > 8
    assert max(draws) - min(draws) > 0.01


# ---- byte-bounded LRU -------------------------------------------------------


def test_lru_byte_bound_eviction():
    d = LruDict("t_serve_lru", cap=64, max_bytes=100, weigher=len)
    d["a"] = b"x" * 40
    d["b"] = b"y" * 40
    assert d.total_bytes == 80
    d["c"] = b"z" * 40  # 120 > 100: 'a' (oldest) evicts
    assert d.get("a") is None
    assert d.total_bytes == 80
    assert d.evictions == 1
    # touching 'b' makes 'c' the eviction victim for the next insert
    assert d.get("b") is not None
    d["e"] = b"w" * 40
    assert d.get("c") is None and d.get("b") is not None
    d.pop("b")
    assert d.total_bytes == 40


# ---- result cache units -----------------------------------------------------


def _cache(**overrides):
    base = {"spark.tpu.serve.resultCache.enabled": True}
    base.update(overrides)
    return ResultCache(RuntimeConf(base))


def test_result_cache_single_flight_one_execution():
    cache = _cache()
    tbl = pa.table({"x": [1, 2, 3]})
    calls = []
    gate = threading.Event()

    def execute():
        calls.append(1)
        gate.wait(timeout=30)
        return tbl

    results, errors = [], []

    def worker():
        try:
            results.append(cache.get_or_execute(("k",), execute))
        except Exception as e:  # pragma: no cover - failure surface
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    time.sleep(0.2)  # let the herd pile onto the flight
    gate.set()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    assert len(calls) == 1  # exactly one device execution
    blobs = {blob for blob, _ in results}
    assert len(blobs) == 1
    assert ipc_to_table(next(iter(blobs))).equals(tbl)
    statuses = sorted(s for _, s in results)
    assert statuses.count("miss") == 1


def test_result_cache_error_propagates_then_recovers():
    cache = _cache()
    boom = [True]

    def execute():
        if boom[0]:
            raise ValueError("planned failure")
        return pa.table({"x": [7]})

    with pytest.raises(ValueError, match="planned failure"):
        cache.get_or_execute(("err",), execute)
    boom[0] = False  # the failed flight must not wedge the key
    blob, status = cache.get_or_execute(("err",), execute)
    assert status == "miss"
    assert ipc_to_table(blob).to_pydict() == {"x": [7]}


def test_result_cache_oversized_result_served_not_cached():
    cache = _cache(**{"spark.tpu.serve.resultCache.maxBytes": 64})
    big = pa.table({"x": list(range(4096))})
    blob, status = cache.get_or_execute(("big",), lambda: big)
    assert status == "miss" and len(blob) > 64
    assert cache.lookup(("big",)) is None  # never cached
    assert ipc_to_table(blob).equals(big)


# ---- connect-server cache hook ---------------------------------------------


def test_cache_invalidation_on_source_rewrite(spark, tmp_path,
                                              serve_conf):
    """The satellite sequence: write parquet -> query (miss) ->
    re-query (hit, byte-identical) -> rewrite the file -> re-query
    must miss and return the NEW data."""
    p = _write_parquet(os.path.join(str(tmp_path), "inv.parquet"), 64)
    spark.read.parquet(p).createOrReplaceTempView("serve_inv")
    serve_conf.set("spark.tpu.serve.resultCache.enabled", True)
    srv = ConnectServer(spark, port=0).start()
    q = "SELECT a, b FROM serve_inv WHERE a >= 4"
    try:
        code1, body1, h1 = _post_sql(srv.url, q)
        assert code1 == 200 and h1.get("X-Cache") == "miss"
        code2, body2, h2 = _post_sql(srv.url, q)
        assert code2 == 200 and h2.get("X-Cache") == "hit"
        assert body2 == body1  # byte-identical, same serialized stream
        # rewrite with different data; bump mtime past fs granularity
        _write_parquet(p, 32, offset=100)
        st = os.stat(p)
        os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
        code3, body3, h3 = _post_sql(srv.url, q)
        assert code3 == 200 and h3.get("X-Cache") == "miss"
        t3 = ipc_to_table(body3)
        assert t3.num_rows == 32  # the NEW data, not the stale cache
        assert min(t3.column("a").to_pylist()) == 100
    finally:
        srv.stop()


def test_cache_on_off_sweep_byte_identical(spark, tmp_path,
                                           serve_conf):
    p = _write_parquet(os.path.join(str(tmp_path), "ab.parquet"), 96)
    spark.read.parquet(p).createOrReplaceTempView("serve_ab")
    q = ("SELECT a, SUM(b) AS s FROM serve_ab WHERE a < 80 "
         "GROUP BY a ORDER BY a")
    srv = ConnectServer(spark, port=0).start()
    try:
        code_off, body_off, h_off = _post_sql(srv.url, q)
        assert code_off == 200 and "X-Cache" not in h_off
        serve_conf.set("spark.tpu.serve.resultCache.enabled", True)
        code_miss, body_miss, h_miss = _post_sql(srv.url, q)
        code_hit, body_hit, h_hit = _post_sql(srv.url, q)
        assert h_miss.get("X-Cache") == "miss"
        assert h_hit.get("X-Cache") == "hit"
        # cached and uncached executions serialize identical streams
        assert body_miss == body_off
        assert body_hit == body_off
    finally:
        srv.stop()


def test_single_flight_stress_8_clients_one_execution(spark, tmp_path,
                                                      serve_conf):
    p = _write_parquet(os.path.join(str(tmp_path), "sf.parquet"), 128)
    spark.read.parquet(p).createOrReplaceTempView("serve_sf")
    serve_conf.set("spark.tpu.serve.resultCache.enabled", True)
    q = "SELECT a, b FROM serve_sf WHERE a > 17"
    kd = key_digest(plan_result_key(spark.sql(q)._plan))
    srv = ConnectServer(spark, port=0).start()
    results, errors = [], []
    barrier = threading.Barrier(8)

    def client(i):
        try:
            barrier.wait(timeout=30)
            results.append(Client(srv.url, timeout=120).sql(q))
        except Exception as e:
            errors.append(e)

    try:
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors
        assert len(results) == 8
        ref = results[0]
        assert all(r.equals(ref) for r in results)
        execs = [e for e in metrics.recent(4096)
                 if e.get("kind") == "serve_cache"
                 and e.get("phase") == "execute" and e.get("key") == kd]
        assert len(execs) == 1  # the herd cost ONE device execution
    finally:
        srv.stop()


# ---- federation router ------------------------------------------------------


def test_router_spreads_and_aggregates_health(spark, tmp_path,
                                              serve_conf):
    p = _write_parquet(os.path.join(str(tmp_path), "rt.parquet"), 48)
    spark.read.parquet(p).createOrReplaceTempView("serve_rt")
    serve_conf.set("spark.tpu.serve.policy", "round_robin")
    serve_conf.set("spark.tpu.serve.healthProbeSeconds", 0.0)
    fleet = serve_fleet(spark, replicas=2)
    try:
        seen = set()
        for i in range(4):
            code, body, hdr = _post_sql(
                fleet.url, f"SELECT a FROM serve_rt WHERE a > {i}")
            assert code == 200
            seen.add(hdr.get("X-SparkTpu-Replica"))
        assert seen == {"r0", "r1"}  # round robin used both
        with urllib.request.urlopen(fleet.url + "/health",
                                    timeout=10) as resp:
            h = json.loads(resp.read())
        assert h["status"] == "ok" and h["router"] is True
        assert {r["id"] for r in h["replicas"]} == {"r0", "r1"}
        for r in h["replicas"]:
            assert r["healthy"] is True
            assert "queue_depth" in r and "running" in r
    finally:
        fleet.stop()


def test_router_honors_client_affinity(spark, tmp_path, serve_conf):
    p = _write_parquet(os.path.join(str(tmp_path), "af.parquet"), 48)
    spark.read.parquet(p).createOrReplaceTempView("serve_af")
    serve_conf.set("spark.tpu.serve.policy", "round_robin")
    serve_conf.set("spark.tpu.serve.healthProbeSeconds", 0.0)
    fleet = serve_fleet(spark, replicas=2)
    try:
        c = Client(fleet.url, timeout=60)
        c.sql("SELECT a FROM serve_af WHERE a > 0")
        first = c.affinity
        assert first in ("r0", "r1")
        # round_robin would alternate; the echoed affinity pins us
        for i in range(3):
            c.sql(f"SELECT a FROM serve_af WHERE a > {i + 1}")
            assert c.affinity == first
    finally:
        fleet.stop()


def test_queue_full_sheds_to_other_replica_no_client_429(
        spark, tmp_path, serve_conf):
    """The acceptance scenario: a queue-full burst on one replica
    sheds to the other with ZERO client-visible 429s while the second
    replica has capacity."""
    p = _write_parquet(os.path.join(str(tmp_path), "sh.parquet"), 48)
    spark.read.parquet(p).createOrReplaceTempView("serve_sh")
    serve_conf.set("spark.tpu.serve.policy", "round_robin")
    serve_conf.set("spark.tpu.serve.healthProbeSeconds", 0.0)
    metrics.reset_serve()
    full = ConnectServer(
        spark, port=0, replica_id="full",
        scheduler=QueryScheduler(conf=RuntimeConf(
            {"spark.tpu.scheduler.queueDepth": 0}))).start()
    ok = ConnectServer(spark, port=0, replica_id="ok").start()
    router = FederationRouter([full, ok], conf=spark.conf).start()
    try:
        for i in range(4):
            code, body, hdr = _post_sql(
                router.url, f"SELECT a FROM serve_sh WHERE a > {i}")
            assert code == 200  # never a 429 while 'ok' has capacity
            assert hdr.get("X-SparkTpu-Replica") == "ok"
        stats = metrics.serve_stats()
        assert stats["sheds"] >= 1
        assert stats["rejected"] == 0
    finally:
        router.stop()
        full.stop()
        ok.stop()


def test_all_replicas_saturated_429_min_retry_after(
        spark, tmp_path, serve_conf):
    p = _write_parquet(os.path.join(str(tmp_path), "sat.parquet"), 48)
    spark.read.parquet(p).createOrReplaceTempView("serve_sat")
    serve_conf.set("spark.tpu.serve.healthProbeSeconds", 0.0)
    r0 = ConnectServer(
        spark, port=0, replica_id="s0",
        scheduler=QueryScheduler(conf=RuntimeConf({
            "spark.tpu.scheduler.queueDepth": 0,
            "spark.tpu.scheduler.retryAfterSeconds": 0.07}))).start()
    r1 = ConnectServer(
        spark, port=0, replica_id="s1",
        scheduler=QueryScheduler(conf=RuntimeConf({
            "spark.tpu.scheduler.queueDepth": 0,
            "spark.tpu.scheduler.retryAfterSeconds": 0.03}))).start()
    router = FederationRouter([r0, r1], conf=spark.conf).start()
    try:
        code, body, hdr = _post_sql(router.url,
                                    "SELECT a FROM serve_sat")
        assert code == 429
        detail = json.loads(body)
        # Retry-After = min across replicas: the soonest any queue in
        # the fleet expects capacity
        assert abs(float(hdr["Retry-After"]) - 0.03) < 1e-9
        assert abs(detail["retry_after_s"] - 0.03) < 1e-9
        assert metrics.serve_stats()["rejected"] >= 1
    finally:
        router.stop()
        r0.stop()
        r1.stop()


def test_dispatch_fault_redispatches_no_duplicate(spark, tmp_path,
                                                  serve_conf):
    """Replica death mid-query (fault serve.dispatch): the query is
    NOT lost (bounded re-dispatch to the other replica answers it) and
    NOT duplicated (one device execution for its key)."""
    p = _write_parquet(os.path.join(str(tmp_path), "fd.parquet"), 48)
    spark.read.parquet(p).createOrReplaceTempView("serve_fd")
    serve_conf.set("spark.tpu.serve.resultCache.enabled", True)
    serve_conf.set("spark.tpu.serve.healthProbeSeconds", 0.0)
    serve_conf.set("spark.tpu.faultInjection.serve.dispatch", "nth:1")
    metrics.reset_serve()
    fleet = serve_fleet(spark, replicas=2)
    q = "SELECT a, b FROM serve_fd WHERE a > 23"
    kd = key_digest(plan_result_key(spark.sql(q)._plan))
    try:
        code, body, hdr = _post_sql(fleet.url, q)
        assert code == 200  # the query was not lost
        assert ipc_to_table(body).num_rows == 48 - 24
        assert faults.fire_count(spark.conf, "serve.dispatch") == 1
        stats = metrics.serve_stats()
        assert stats["redispatches"] >= 1
        assert stats["replica_failures"] >= 1
        execs = [e for e in metrics.recent(4096)
                 if e.get("kind") == "serve_cache"
                 and e.get("phase") == "execute" and e.get("key") == kd]
        assert len(execs) == 1  # no duplicate execution
    finally:
        fleet.stop()


def test_dispatch_fault_corrupt_surfaces_unretried(spark, tmp_path,
                                                   serve_conf):
    p = _write_parquet(os.path.join(str(tmp_path), "fc.parquet"), 32)
    spark.read.parquet(p).createOrReplaceTempView("serve_fc")
    serve_conf.set("spark.tpu.serve.healthProbeSeconds", 0.0)
    serve_conf.set("spark.tpu.faultInjection.serve.dispatch",
                   "nth:1:corrupt")
    fleet = serve_fleet(spark, replicas=2)
    try:
        code, body, hdr = _post_sql(fleet.url,
                                    "SELECT a FROM serve_fc")
        # DATA_LOSS is not a replica death: surfaced typed, no retry
        assert code == 500
        assert json.loads(body)["error"] == "InjectedCorruptionError"
    finally:
        fleet.stop()


def test_replica_death_mid_run_fleet_keeps_serving(spark, tmp_path,
                                                   serve_conf):
    p = _write_parquet(os.path.join(str(tmp_path), "rd.parquet"), 48)
    spark.read.parquet(p).createOrReplaceTempView("serve_rd")
    serve_conf.set("spark.tpu.serve.policy", "least_queued")
    serve_conf.set("spark.tpu.serve.healthProbeSeconds", 0.0)
    fleet = serve_fleet(spark, replicas=2)
    try:
        c = Client(fleet.url, timeout=60)
        assert c.sql("SELECT a FROM serve_rd WHERE a > 1") \
            .num_rows == 46
        fleet.replicas[0].stop()  # kill one replica mid-run
        c.affinity = None  # a fresh client must also survive
        for i in range(3):
            t = c.sql(f"SELECT a FROM serve_rd WHERE a > {i + 2}")
            assert t.num_rows == 48 - (i + 3)
        with urllib.request.urlopen(fleet.url + "/health",
                                    timeout=10) as resp:
            h = json.loads(resp.read())
        healthy = {r["id"]: r["healthy"] for r in h["replicas"]}
        assert healthy["r1"] is True
        assert healthy["r0"] is False
    finally:
        fleet.stop()


# ---- observability ----------------------------------------------------------


def test_serve_profile_and_api_endpoint(spark, tmp_path, serve_conf):
    from spark_tpu.ui import StatusServer

    p = _write_parquet(os.path.join(str(tmp_path), "ob.parquet"), 32)
    spark.read.parquet(p).createOrReplaceTempView("serve_ob")
    serve_conf.set("spark.tpu.serve.resultCache.enabled", True)
    fleet = serve_fleet(spark, replicas=2)
    ui = StatusServer(spark, port=0)
    try:
        for _ in range(2):
            code, _, _ = _post_sql(fleet.url,
                                   "SELECT a FROM serve_ob")
            assert code == 200
        prof = tracing.serve_profile()
        assert prof["cache"]["execute"] >= 1
        assert prof["totals"]["dispatches"] >= 2
        text = tracing.format_serve_profile(prof)
        assert "result cache" in text and "router" in text
        with urllib.request.urlopen(ui.url + "/api/v1/serve",
                                    timeout=10) as resp:
            payload = json.loads(resp.read())
        assert "profile" in payload and "counters" in payload
        assert payload["counters"]["dispatches"] >= 2
        assert payload["gauges"].get(
            "serve.result_cache.entries", 0) >= 1
    finally:
        ui.stop()
        fleet.stop()


def test_federation_least_queued_picks_emptier(serve_conf):
    """Policy unit: least_queued picks the replica with the smallest
    queued+running load from the last probe (no HTTP involved)."""
    fed = Federation(
        [("a", "http://127.0.0.1:1"), ("b", "http://127.0.0.1:2")],
        conf=RuntimeConf({"spark.tpu.serve.policy": "least_queued"}))
    fed.replicas[0].queue_depth = 5
    fed.replicas[0].last_probe = time.time() + 3600
    fed.replicas[1].queue_depth = 1
    fed.replicas[1].last_probe = time.time() + 3600
    assert fed.pick().id == "b"
    fed.replicas[1].running = 9  # load = queued + running
    assert fed.pick().id == "a"
    assert fed.pick(affinity="b").id == "b"  # affinity wins
    fed.replicas[1].healthy = False
    assert fed.pick(affinity="b").id == "a"  # unless unhealthy

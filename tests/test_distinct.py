"""DISTINCT aggregation vs an external (pandas) oracle.

The round-1 engine silently computed plain COUNT for countDistinct
(VERDICT Weak #3); these tests pin the fixed semantics on both the
single-device and the mesh engine, checked against pandas — an
independent implementation, unlike the self-referential oracle the
round-1 distributed tests used. Reference semantics:
sql/catalyst/.../optimizer/RewriteDistinctAggregates.scala:1.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_tpu.api import functions as F
from spark_tpu.columnar.arrow import from_arrow
from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L


def _table(rng, n=500, nulls=True):
    k = rng.integers(0, 7, n)
    v = rng.integers(0, 10, n)
    valid = rng.random(n) > 0.15 if nulls else np.ones(n, bool)
    return pa.table({
        "k": pa.array(k, pa.int64()),
        "v": pa.array(v, pa.int64(), mask=~valid),
    })


def _oracle_grouped(tbl):
    df = tbl.to_pandas()
    g = df.groupby("k")["v"]
    return {
        int(k): (int(s.nunique()), int(s.dropna().unique().sum()),
                 int(s.count()))
        for k, s in g
    }


def _run_single(plan):
    from spark_tpu.physical.planner import execute_logical

    return execute_logical(plan).to_pylist()


def _run_mesh(plan):
    from spark_tpu.parallel.executor import MeshExecutor
    from spark_tpu.parallel.mesh import make_mesh

    ex = MeshExecutor(make_mesh(8))
    return ex.execute_logical(plan).to_pylist()


AGGS = (
    E.Col("k"),
    E.Alias(E.Count(E.Col("v"), distinct=True), "cd"),
    E.Alias(E.Sum(E.Col("v"), distinct=True), "sd"),
    E.Alias(E.Count(E.Col("v")), "c"),
)


@pytest.mark.parametrize("runner", [_run_single, _run_mesh])
def test_grouped_count_sum_distinct(rng, runner):
    tbl = _table(rng)
    plan = L.Aggregate((E.Col("k"),), AGGS, L.Relation(from_arrow(tbl)))
    rows = {r["k"]: (r["cd"], r["sd"], r["c"]) for r in runner(plan)}
    assert rows == _oracle_grouped(tbl)


@pytest.mark.parametrize("runner", [_run_single, _run_mesh])
def test_global_count_distinct(rng, runner):
    tbl = _table(rng)
    plan = L.Aggregate(
        (),
        (E.Alias(E.Count(E.Col("v"), distinct=True), "cd"),
         E.Alias(E.Sum(E.Col("v"), distinct=True), "sd"),
         E.Alias(E.Avg(E.Col("v"), distinct=True), "ad"),
         E.Alias(E.Count(None), "n")),
        L.Relation(from_arrow(tbl)))
    [r] = runner(plan)
    s = tbl.to_pandas()["v"]
    uniq = s.dropna().unique()
    assert r["cd"] == len(uniq)
    assert r["sd"] == int(uniq.sum())
    assert r["ad"] == pytest.approx(uniq.mean())
    assert r["n"] == len(s)


def test_verdict_repro_exact():
    """The exact silent-wrong-result repro from VERDICT Weak #3."""
    from spark_tpu.api.session import SparkSession

    spark = SparkSession.builder.getOrCreate()
    df = spark.createDataFrame(pa.table({
        "k": pa.array([1, 1, 1, 2, 2], pa.int64()),
        "v": pa.array([5, 5, 5, 7, 8], pa.int64()),
    }))
    rows = {r["k"]: r["cd"]
            for r in df.groupBy("k")
            .agg(E.Alias(F.countDistinct("v"), "cd")).collect()}
    assert rows == {1: 1, 2: 2}


@pytest.mark.parametrize("runner", [_run_single, _run_mesh])
def test_distinct_string_values(rng, runner):
    words = np.array(["apple", "pear", "plum", "fig"])
    k = rng.integers(0, 3, 200)
    w = words[rng.integers(0, 4, 200)]
    tbl = pa.table({"k": pa.array(k, pa.int64()), "w": pa.array(w)})
    plan = L.Aggregate(
        (E.Col("k"),),
        (E.Col("k"), E.Alias(E.Count(E.Col("w"), distinct=True), "cd")),
        L.Relation(from_arrow(tbl)))
    got = {r["k"]: r["cd"] for r in runner(plan)}
    want = {int(kk): int(s.nunique())
            for kk, s in pd.DataFrame({"k": k, "w": w}).groupby("k")["w"]}
    assert got == want

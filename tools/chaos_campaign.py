#!/usr/bin/env python
"""Seeded chaos campaign over a live in-process serving fleet.

Drives the full stack — Client -> FederationRouter -> replica
ConnectServers -> scheduler -> engine — through randomized multi-point
fault schedules (spark_tpu/chaos.py), asserting the fleet-grade
resilience contract on every one: byte-identical-or-typed-error, zero
hangs, retry attempts bounded by the unified budget, and the HBM
invariant ``execution + storage <= budget``. Also runs two directed
scenarios the random sweep can't guarantee to hit:

- **kill-one-replica**: stop a replica's HTTP server mid-campaign,
  watch its circuit breaker open on the dispatch failure, revive the
  replica on the SAME port, and assert the breaker walks
  open -> half_open -> closed as the probe request succeeds.
- **A/B attempts**: the same fault-heavy schedule with the unified
  retry budget DISABLED (legacy multiplicative per-layer caps) vs
  ENABLED, comparing total attempt draws.

Usage:
  python tools/chaos_campaign.py --seed 7 --schedules 25
  python tools/chaos_campaign.py --replay /tmp/chaos_fail.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import pyarrow as pa  # noqa: E402
import pyarrow.parquet as pq  # noqa: E402

from spark_tpu import chaos, faults, metrics  # noqa: E402
from spark_tpu import recovery  # noqa: E402
from spark_tpu.connect.server import Client, ConnectServer  # noqa: E402
from spark_tpu.serve.router import serve_fleet  # noqa: E402

#: the mixed workload: scan+filter, aggregation, and a join — together
#: they cross every engine-side injection point the campaign arms
_QUERIES = (
    "SELECT a, b FROM t WHERE a >= 8",
    "SELECT a % 4 AS g, SUM(b) AS s, COUNT(*) AS n FROM t "
    "GROUP BY a % 4",
    "SELECT t.a, t.b, u.c FROM t JOIN u ON t.a = u.a WHERE u.c < 40",
)


def _make_session(tmp):
    from spark_tpu.api.session import SparkSession

    sess = SparkSession.builder.getOrCreate()
    t = pa.table({"a": list(range(96)),
                  "b": [float(i) * 0.5 for i in range(96)]})
    u = pa.table({"a": list(range(0, 96, 2)),
                  "c": [i % 48 for i in range(48)]})
    pt, pu = os.path.join(tmp, "t.parquet"), os.path.join(
        tmp, "u.parquet")
    pq.write_table(t, pt)
    pq.write_table(u, pu)
    sess.read.parquet(pt).createOrReplaceTempView("t")
    sess.read.parquet(pu).createOrReplaceTempView("u")
    #: backing files, for the fleet family's append scenario
    sess._chaos_tables = {"t": pt, "u": pu}
    return sess


def _result_bytes(table: pa.Table) -> bytes:
    return json.dumps(table.to_pydict(), sort_keys=True).encode()


def _clear_caches(session, fleet=None) -> None:
    """Faults must reach the engine, not a cached blob — drop the
    shared session cache AND (ownership mode) every replica-local
    one."""
    rc = getattr(session, "serve_result_cache", None)
    if rc is not None:
        rc.clear()
    for s in (fleet.replicas if fleet is not None else ()):
        c = getattr(s, "result_cache", None)
        if c is not None:
            c.clear()


def _workload(session, url: str, timeout: float, fleet=None):
    """One campaign iteration: all queries through a FRESH client (no
    carried affinity) against the fleet; returns concatenated
    deterministic bytes."""
    _clear_caches(session, fleet)
    client = Client(url, timeout=timeout, retries=3)
    out = []
    for q in _QUERIES:
        out.append(_result_bytes(client.sql(q)))
    return b"\x00".join(out)


def _campaign(session, fleet, args) -> bool:
    conf = session.conf
    clean = _workload(session, fleet.url, args.timeout, fleet)
    # serve-tier points need the fleet; engine points fire inside the
    # replicas — arm everything
    schedules = chaos.generate_campaign(args.seed, args.schedules)
    print(f"chaos campaign: seed={args.seed} "
          f"schedules={args.schedules} family={args.family}")
    report = chaos.run_campaign(
        conf,
        lambda: _workload(session, fleet.url, args.timeout, fleet),
        schedules, clean_bytes=clean, alarm_s=args.alarm,
        queries=len(_QUERIES),
        memory_manager=session.memory_manager,
        artifact_path=args.artifact, log=print)
    print(json.dumps(report.summary(), indent=2))
    return report.ok


def _replay(session, fleet, args) -> bool:
    sch = chaos.replay_artifact(args.replay)
    print(f"replaying schedule #{sch.index} "
          f"(campaign seed {sch.campaign_seed}): {sch.describe()}")
    clean = _workload(session, fleet.url, args.timeout)
    r = chaos.run_schedule(
        session.conf,
        lambda: _workload(session, fleet.url, args.timeout),
        sch, clean_bytes=clean, alarm_s=args.alarm,
        queries=len(_QUERIES),
        memory_manager=session.memory_manager)
    print(json.dumps(r.to_dict(), indent=2))
    return r.ok


def _kill_revive(session, fleet, args) -> bool:
    """Directed breaker scenario: kill -> open -> revive ->
    half_open -> closed."""
    conf = session.conf
    fed = fleet.router.federation
    conf.set("spark.tpu.serve.breaker.openSeconds", 0.3)
    # throttle background health probes so the DISPATCH is what finds
    # the corpse (the router's /health check would otherwise sideline
    # the replica first and no forward would ever fail against it);
    # breaker.trip() opens on that single connection failure — no
    # minRequests warm-up needed. Probes are driven explicitly with
    # probe(force=True).
    conf.set("spark.tpu.serve.healthProbeSeconds", 3600.0)
    try:
        # the random sweep may have left stale unhealthy flags and a
        # success-heavy breaker window from injected dispatch faults;
        # re-probe and reset so this scenario starts from a live fleet
        # with empty windows (one failure must reach failureRate)
        fed.probe(force=True)
        for r in fed.replicas:
            r.breaker.reset()
        client = Client(fleet.url, timeout=args.timeout, retries=3)
        _result_bytes(client.sql(_QUERIES[0]))
        victim_id = client.affinity
        victim = next(s for s in fleet.replicas
                      if s.replica_id == victim_id)
        rep = next(r for r in fed.replicas if r.id == victim_id)
        host, port = victim.host, victim.port
        print(f"kill-revive: stopping replica {victim_id} "
              f"({host}:{port})")
        victim.stop()
        # the affinity-routed request hits the dead replica, fails,
        # re-dispatches, and trip() opens the breaker on that single
        # connection failure
        _result_bytes(client.sql(_QUERIES[1]))
        state = rep.breaker.state
        print(f"  after dispatch failure: breaker={state}")
        if state != "open":
            print("  FAIL: breaker did not open")
            return False
        revived = ConnectServer(session, host=host, port=port,
                                replica_id=victim_id).start()
        fleet.replicas.append(revived)
        time.sleep(0.35)  # let openSeconds elapse
        fed.probe(force=True)  # router sees the replica alive again
        deadline_t = time.time() + 10.0
        transitions = []
        while rep.breaker.state != "closed" \
                and time.time() < deadline_t:
            client.affinity = victim_id  # aim the probe at it
            _result_bytes(client.sql(_QUERIES[0]))
            time.sleep(0.05)
        transitions = [(a, b) for _, a, b in rep.breaker.state_changes]
        print(f"  transitions: {transitions}")
        ok = (("closed", "open") in transitions
              and ("open", "half_open") in transitions
              and ("half_open", "closed") in transitions
              and rep.breaker.state == "closed")
        print(f"  kill-revive: {'ok' if ok else 'FAIL'} "
              f"(final={rep.breaker.state})")
        return ok
    finally:
        conf.unset("spark.tpu.serve.breaker.openSeconds")
        conf.unset("spark.tpu.serve.healthProbeSeconds")


def _ab_attempts(session, fleet, args) -> bool:
    """Same fault-heavy schedule, legacy vs budgeted retry
    accounting."""
    conf = session.conf
    fed = fleet.router.federation
    spec = f"prob:0.4:{args.seed}:transient"
    counts = {}
    for label, enabled in (("legacy", False), ("budgeted", True)):
        # the previous leg's injected dispatch faults leave replicas
        # flagged unhealthy; start each leg from a live fleet so both
        # sides exercise the same dispatch path
        fed.probe(force=True)
        for r in fed.replicas:
            r.breaker.reset()
        conf.set("spark.tpu.recovery.retryBudget.enabled", enabled)
        conf.set("spark.tpu.faultInjection.serve.dispatch", spec)
        conf.set("spark.tpu.faultInjection.execute.device", spec)
        faults.reset(conf)
        before = metrics.retry_budget_stats()
        try:
            for _ in range(3):
                try:
                    _workload(session, fleet.url, args.timeout)
                except Exception:
                    pass  # typed failures are fine; counting attempts
        finally:
            conf.unset("spark.tpu.faultInjection.serve.dispatch")
            conf.unset("spark.tpu.faultInjection.execute.device")
            conf.unset("spark.tpu.recovery.retryBudget.enabled")
            faults.reset(conf)
        after = metrics.retry_budget_stats()
        if enabled:
            counts[label] = (after["draws"] - before["draws"]
                             + after["floor_draws"]
                             - before["floor_draws"])
        else:
            counts[label] = (after["legacy_attempts"]
                             - before["legacy_attempts"])
    budget = int(conf.get(recovery.RETRY_BUDGET_ATTEMPTS))
    cap = 3 * len(_QUERIES) * budget
    ok = counts["budgeted"] <= cap
    print(f"A/B attempts: legacy={counts['legacy']} "
          f"budgeted={counts['budgeted']} "
          f"(cap {cap}: 3 iters x {len(_QUERIES)} queries x "
          f"{budget} budget) -> {'ok' if ok else 'FAIL'}")
    return ok


# -- fleet family (--family fleet): ownership, epochs, coherence -----------


def _owner_of(fed, table: str = "t"):
    """(owner replica id, shard key) of ``table`` under the current
    ownership map."""
    snap = fed.ownership.snapshot()
    shard = snap["tables"].get(table)
    return (snap["shards"].get(shard), shard)


def _revive(session, fleet, replica_id: str, host: str, port: int):
    """Restart a stopped replica on its original port, with its own
    invalidation-subscribed ResultCache (the ownership-mode shape
    serve_fleet builds)."""
    from spark_tpu.serve.ownership import session_invalidation_log
    from spark_tpu.serve.result_cache import ResultCache

    cache = ResultCache(session.conf).attach_invalidation_log(
        session_invalidation_log(session))
    server = ConnectServer(session, host=host, port=port,
                           replica_id=replica_id,
                           result_cache=cache).start()
    # replace the corpse, don't accumulate it: later scenarios find
    # their victim by replica_id and must get the LIVE server
    fleet.replicas[:] = [s for s in fleet.replicas
                         if s.replica_id != replica_id]
    fleet.replicas.append(server)
    return server


def _live_server(fleet, replica_id: str):
    """The running ConnectServer with this id (stop() nulls _thread)."""
    return next(s for s in fleet.replicas
                if s.replica_id == replica_id
                and s._thread is not None)


def _fleet_kill_owner(session, fleet, args) -> bool:
    """Kill the replica OWNING table t's shard: a new epoch must mint,
    the shard must re-map to a survivor, and the workload must stay
    byte-identical with no hang. The corpse is revived afterwards so
    later scenarios start from a full fleet."""
    fed = fleet.router.federation
    conf = session.conf
    conf.set("spark.tpu.serve.healthProbeSeconds", 3600.0)
    victim = None
    try:
        fed.probe(force=True)
        for r in fed.replicas:
            r.breaker.reset()
        clean = _workload(session, fleet.url, args.timeout, fleet)
        owner, shard = _owner_of(fed)
        if owner is None:
            print("kill-owner: FAIL (no shard owner learned)")
            return False
        epoch0 = fed.ownership.epoch
        victim = _live_server(fleet, owner)
        print(f"kill-owner: stopping owner {owner} of shard {shard}")
        t0 = time.time()
        victim.stop()
        after = _workload(session, fleet.url, args.timeout, fleet)
        elapsed = time.time() - t0
        new_owner, _ = _owner_of(fed)
        ok = (after == clean
              and fed.ownership.epoch > epoch0
              and new_owner not in (None, owner)
              and elapsed < args.alarm)
        print(f"  epoch {epoch0}->{fed.ownership.epoch}, owner "
              f"{owner}->{new_owner}, bytes "
              f"{'identical' if after == clean else 'MISMATCH'}, "
              f"{elapsed:.1f}s -> {'ok' if ok else 'FAIL'}")
        return ok
    finally:
        if victim is not None:
            _revive(session, fleet, victim.replica_id,
                    victim.host, victim.port)
        conf.unset("spark.tpu.serve.healthProbeSeconds")
        fed.probe(force=True)


def _fleet_kill_revive_owner(session, fleet, args) -> bool:
    """Kill the owner, serve through the failover map, revive the SAME
    replica id on the SAME port: ANOTHER epoch must mint on rejoin,
    the shard must return to its rendezvous owner, and bytes must hold
    through the whole death->failover->rejoin arc."""
    fed = fleet.router.federation
    conf = session.conf
    conf.set("spark.tpu.serve.healthProbeSeconds", 3600.0)
    conf.set("spark.tpu.serve.breaker.openSeconds", 0.3)
    try:
        fed.probe(force=True)
        for r in fed.replicas:
            r.breaker.reset()
        clean = _workload(session, fleet.url, args.timeout, fleet)
        owner, shard = _owner_of(fed)
        if owner is None:
            print("kill-and-revive-owner: FAIL (no owner learned)")
            return False
        epoch0 = fed.ownership.epoch
        victim = _live_server(fleet, owner)
        host, port = victim.host, victim.port
        print(f"kill-and-revive-owner: stopping owner {owner}")
        victim.stop()
        during = _workload(session, fleet.url, args.timeout, fleet)
        epoch_failover = fed.ownership.epoch
        _revive(session, fleet, owner, host, port)
        time.sleep(0.35)  # openSeconds elapses -> half-open probe
        fed.probe(force=True)  # rejoin: membership change, new epoch
        after = _workload(session, fleet.url, args.timeout, fleet)
        back_owner, _ = _owner_of(fed)
        ok = (during == clean and after == clean
              and epoch_failover > epoch0
              and fed.ownership.epoch > epoch_failover
              and back_owner == owner)
        print(f"  epochs {epoch0}->{epoch_failover}->"
              f"{fed.ownership.epoch}, shard owner back on "
              f"{back_owner} -> {'ok' if ok else 'FAIL'}")
        return ok
    finally:
        conf.unset("spark.tpu.serve.healthProbeSeconds")
        conf.unset("spark.tpu.serve.breaker.openSeconds")
        fed.probe(force=True)


def _fleet_partition(session, fleet, args) -> bool:
    """Partition the router from one live replica (its URL is swapped
    for a black hole — the replica itself never dies): dispatch trips
    the breaker, ownership re-maps, queries route around it. Healing
    the partition and re-probing rejoins it with another epoch."""
    fed = fleet.router.federation
    conf = session.conf
    conf.set("spark.tpu.serve.healthProbeSeconds", 3600.0)
    try:
        fed.probe(force=True)
        for r in fed.replicas:
            r.breaker.reset()
        clean = _workload(session, fleet.url, args.timeout, fleet)
        owner, _ = _owner_of(fed)
        rep = next(r for r in fed.replicas if r.id == owner)
        real_url = rep.url
        epoch0 = fed.ownership.epoch
        # a port nothing listens on: connection refused = partition
        rep.url = "http://127.0.0.1:9"
        print(f"partition-router-from-replica: black-holing {owner}")
        during = _workload(session, fleet.url, args.timeout, fleet)
        partitioned = (during == clean
                       and fed.ownership.epoch > epoch0
                       and rep.breaker.state == "open")
        rep.url = real_url
        fed.probe(force=True)  # heal: replica rejoins, epoch mints
        healed_epoch = fed.ownership.epoch
        after = _workload(session, fleet.url, args.timeout, fleet)
        ok = (partitioned and after == clean
              and rep.healthy and healed_epoch > epoch0 + 1)
        print(f"  routed-around={'ok' if partitioned else 'FAIL'}, "
              f"rejoin epoch={healed_epoch}, bytes "
              f"{'identical' if after == clean else 'MISMATCH'} "
              f"-> {'ok' if ok else 'FAIL'}")
        return ok
    finally:
        conf.unset("spark.tpu.serve.healthProbeSeconds")
        fed.probe(force=True)


def _fleet_stale_read(session, fleet, args) -> bool:
    """Append to table t's backing file while every replica holds a
    TTL'd fingerprint probe AND a cached result: the invalidation
    broadcast (not TTL expiry) must kill the stale window. The check
    reads through replicas that never touched the source after the
    append — their only signal is the broadcast. Runs LAST (it grows
    table t)."""
    conf = session.conf
    q = "SELECT a, b FROM t WHERE a >= 8"
    path = session._chaos_tables["t"]
    conf.set("spark.tpu.serve.fingerprintCacheSeconds", 300.0)
    try:
        _clear_caches(session, fleet)
        live = [s for s in fleet.replicas
                if getattr(s, "_thread", None) is not None]
        # warm every replica DIRECTLY: pre-append bytes + fp probe
        pre = {}
        for s in live:
            c = Client(s.url, timeout=args.timeout, retries=3)
            pre[s.replica_id] = _result_bytes(c.sql(q))
            assert c.last_query["cache"] in ("miss", "hit")
        # the append commits
        old = pq.read_table(path)
        grown = pa.concat_tables([old, pa.table({
            "a": [1000 + i for i in range(8)],
            "b": [float(i) for i in range(8)]})])
        pq.write_table(grown, path)
        # the appender's own re-read detects the rewrite and
        # broadcasts the invalidation fleet-wide
        log = session.serve_invalidation_log
        v0 = log.version
        session.sql("SELECT COUNT(*) AS n FROM t").collect()
        if log.version <= v0:
            print("stale-read: FAIL (no invalidation broadcast)")
            return False
        stale = []
        for s in live:
            c = Client(s.url, timeout=args.timeout, retries=3)
            got = _result_bytes(c.sql(q))
            if got == pre[s.replica_id]:
                stale.append(s.replica_id)
        ok = not stale
        print(f"stale-read: broadcast v{v0}->{log.version}, "
              f"{len(live)} replicas re-read fresh"
              + (f", STALE on {stale}" if stale else "")
              + f" -> {'ok' if ok else 'FAIL'}")
        return ok
    finally:
        conf.unset("spark.tpu.serve.fingerprintCacheSeconds")
        _clear_caches(session, fleet)


def _fleet_scenarios(session, fleet, args) -> bool:
    ok = _fleet_kill_owner(session, fleet, args)
    ok = _fleet_kill_revive_owner(session, fleet, args) and ok
    ok = _fleet_partition(session, fleet, args) and ok
    ok = _fleet_stale_read(session, fleet, args) and ok
    return ok


# -- overload family (--family overload): SLO predict -> schedule -> shed --


#: set BEFORE serve_fleet — the scheduler reads worker count and
#: constructs its SloController at build time. A deliberately small
#: fleet (2 workers, 16-deep queue per replica) so the saturation
#: scenarios reach genuine 2x overload with a handful of threads.
_OVERLOAD_CONF = {
    "spark.tpu.slo.enabled": True,
    "spark.tpu.scheduler.maxConcurrency": 2,
    "spark.tpu.scheduler.queueDepth": 16,
    "spark.tpu.slo.controller.windowSeconds": 2.0,
    "spark.tpu.slo.controller.minPredictions": 5,
}


def _live(fleet):
    return [s for s in fleet.replicas
            if getattr(s, "_thread", None) is not None]


def _train_fleet(fleet, args, n: int = 4) -> None:
    """Warm every replica's latency model DIRECTLY (the router would
    concentrate training on whichever replica won affinity) and wait
    until each one predicts the scan query's fingerprint."""
    from spark_tpu.slo.model import fingerprint_sql

    fp = fingerprint_sql(_QUERIES[0])
    for s in _live(fleet):
        c = Client(s.url, timeout=args.timeout, retries=3)
        for q in _QUERIES:
            for _ in range(n):
                c.sql(q)
        deadline_t = time.time() + 10.0
        while s.scheduler._slo.model.predict_run_ms(fp) is None \
                and time.time() < deadline_t:
            time.sleep(0.02)
        assert s.scheduler._slo.model.predict_run_ms(fp) is not None, \
            f"model never trained on replica {s.replica_id}"


def _overload_saturation(session, fleet, args) -> bool:
    """Sustained ~2x saturation with comfortable deadlines: every
    outcome is a success or a typed error, no client thread hangs, and
    the fleet keeps serving (some successes) the whole time."""
    fed = fleet.router.federation
    fed.probe(force=True)
    for r in fed.replicas:
        r.breaker.reset()
    _clear_caches(session, fleet)
    _train_fleet(fleet, args)
    outcomes = []
    lock = threading.Lock()

    def worker(i):
        c = Client(fleet.url, timeout=args.timeout, retries=2)
        for j in range(3):
            try:
                c.sql(_QUERIES[(i + j) % len(_QUERIES)],
                      deadline_s=args.timeout)
                with lock:
                    outcomes.append(("ok", None))
            except Exception as e:  # classified below
                with lock:
                    outcomes.append(("err", e))

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(16)]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join(args.alarm)
    elapsed = time.time() - t0
    hung = sum(1 for t in threads if t.is_alive())
    untyped = [e for k, e in outcomes
               if k == "err" and not chaos.is_typed_error(e)]
    n_ok = sum(1 for k, _ in outcomes if k == "ok")
    ok = (hung == 0 and not untyped and n_ok > 0
          and len(outcomes) == 16 * 3)
    print(f"overload-saturation: {len(outcomes)} outcomes "
          f"({n_ok} ok, {len(outcomes) - n_ok} typed) in "
          f"{elapsed:.1f}s, hung={hung}, untyped={len(untyped)} "
          f"-> {'ok' if ok else 'FAIL'}")
    for e in untyped[:3]:
        print(f"  untyped: {e!r}")
    return ok


def _overload_deadline_mix(session, fleet, args) -> bool:
    """Doomed deadlines shed EARLY with the typed InfeasibleDeadline
    (the reject round-trip costs milliseconds, never the deadline or a
    queue slot); interleaved loose deadlines keep succeeding through
    the same fleet. deadline_s is relative and converted at the
    replica, so the check is deterministic once the model is warm."""
    from spark_tpu.slo.edf import InfeasibleDeadline

    _clear_caches(session, fleet)
    _train_fleet(fleet, args)
    rejects0 = metrics.slo_stats()["rejects"]
    c = Client(fleet.url, timeout=args.timeout, retries=2)
    shed_ms, wrong = [], []
    for i in range(12):
        tight = i % 2 == 0
        t0 = time.time()
        try:
            c.sql(_QUERIES[0],
                  deadline_s=0.0005 if tight else args.timeout)
            if tight:
                wrong.append(f"tight #{i} was served")
        except InfeasibleDeadline:
            if tight:
                shed_ms.append((time.time() - t0) * 1e3)
            else:
                wrong.append(f"loose #{i} rejected")
        except Exception as e:
            wrong.append(f"#{i} ({'tight' if tight else 'loose'}): "
                         f"{e!r}")
    rejected = metrics.slo_stats()["rejects"] - rejects0
    worst = max(shed_ms) if shed_ms else float("inf")
    ok = not wrong and rejected >= 6 and worst < 2000.0
    print(f"overload-deadline-mix: {len(shed_ms)}/6 tight shed typed "
          f"(worst round-trip {worst:.1f}ms), {rejected} admission "
          f"rejects, {len(wrong)} wrong -> {'ok' if ok else 'FAIL'}")
    for w in wrong[:4]:
        print(f"  wrong: {w}")
    return ok


def _overload_brownout_flap(session, fleet, args) -> bool:
    """Predictive brownout ENTERS under saturation (predicted p99
    blows past the target while queries are merely queued, not yet
    late) and EXITS once the queues drain — level back to 0, no flap
    residue. Targets are pinned per-controller to 3x that replica's
    own trained run prediction so the scenario measures QUEUEING, not
    absolute machine speed."""
    from spark_tpu.slo.model import fingerprint_sql

    _clear_caches(session, fleet)
    _train_fleet(fleet, args)
    fp = fingerprint_sql(_QUERIES[0])
    stats0 = metrics.slo_stats()
    saved = {}
    ctls = {s.replica_id: (s, s.scheduler._slo) for s in _live(fleet)}
    for rid, (s, ctl) in ctls.items():
        pred = ctl.model.predict_run_ms(fp) or 10.0
        with ctl._lock:
            saved[rid] = ctl._target_ms
            ctl._target_ms = 3.0 * pred
    try:
        def burst(i):
            c = Client(fleet.url, timeout=args.timeout, retries=2)
            for _ in range(2):
                try:
                    c.sql(_QUERIES[0], deadline_s=args.timeout)
                except Exception:
                    pass  # typed shedding under burst is fine here

        threads = [threading.Thread(target=burst, args=(i,),
                                    daemon=True) for i in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(args.alarm)
        entered = [rid for rid, (s, ctl) in ctls.items()
                   if ctl.brownout_level() == 1]
        if not entered:
            print("overload-brownout-flap: FAIL (no controller "
                  "entered brownout under 24-thread burst)")
            return False
        # drain, then trickle light load at the browned-out replicas:
        # predictions fall back to bare run time, the hot window ages
        # out, and the controller exits with hysteresis
        time.sleep(2.2)
        deadline_t = time.time() + 20.0
        while time.time() < deadline_t and any(
                ctls[rid][1].brownout_level() == 1 for rid in entered):
            for rid in entered:
                s, ctl = ctls[rid]
                if ctl.brownout_level() == 1:
                    Client(s.url, timeout=args.timeout,
                           retries=2).sql(_QUERIES[0])
            time.sleep(0.25)
        still = [rid for rid in entered
                 if ctls[rid][1].brownout_level() == 1]
        stats = metrics.slo_stats()
        ok = (not still
              and stats["brownout_enters"] > stats0["brownout_enters"]
              and stats["brownout_exits"] > stats0["brownout_exits"])
        print(f"overload-brownout-flap: entered on {entered}, "
              f"exits={stats['brownout_exits'] - stats0['brownout_exits']}, "
              f"stuck={still} -> {'ok' if ok else 'FAIL'}")
        return ok
    finally:
        for rid, (s, ctl) in ctls.items():
            with ctl._lock:
                ctl._target_ms = saved[rid]
        metrics.set_brownout(0)


def _overload_scenarios(session, fleet, args) -> bool:
    ok = _overload_saturation(session, fleet, args)
    ok = _overload_deadline_mix(session, fleet, args) and ok
    ok = _overload_brownout_flap(session, fleet, args) and ok
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--schedules", type=int, default=25)
    ap.add_argument("--alarm", type=float, default=90.0,
                    help="per-schedule wall-clock bound (zero-hang)")
    ap.add_argument("--timeout", type=float, default=30.0,
                    help="client per-request timeout (mints the "
                         "propagated deadline)")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--artifact",
                    default=os.path.join(tempfile.gettempdir(),
                                         "chaos_fail.json"),
                    help="replayable JSON written on first failure")
    ap.add_argument("--replay", default=None,
                    help="re-run one failing schedule from artifact")
    ap.add_argument("--skip-scenarios", action="store_true",
                    help="random sweep only (no directed scenarios)")
    ap.add_argument("--family", choices=("core", "fleet", "overload"),
                    default="core",
                    help="core = policy-routed fleet + kill-revive/AB "
                         "scenarios; fleet = ownership mode (epochs, "
                         "owner routing, coherent caches) + "
                         "kill-owner / kill-and-revive-owner / "
                         "partition / stale-read scenarios; overload "
                         "= SLO mode on a deliberately small fleet + "
                         "sustained-saturation / deadline-mix / "
                         "brownout-flap scenarios (shed early, never "
                         "hang)")
    args = ap.parse_args(argv)

    with tempfile.TemporaryDirectory() as tmp:
        session = _make_session(tmp)
        if args.family == "fleet":
            session.conf.set("spark.tpu.serve.ownership.enabled", True)
            session.conf.set("spark.tpu.serve.resultCache.enabled",
                             True)
        elif args.family == "overload":
            for k, v in _OVERLOAD_CONF.items():
                session.conf.set(k, v)
        fleet = serve_fleet(session, replicas=args.replicas)
        try:
            if args.replay:
                ok = _replay(session, fleet, args)
            else:
                ok = _campaign(session, fleet, args)
                if not args.skip_scenarios \
                        and args.family == "fleet":
                    ok = _fleet_scenarios(session, fleet, args) and ok
                elif not args.skip_scenarios \
                        and args.family == "overload":
                    ok = _overload_scenarios(session, fleet,
                                             args) and ok
                elif not args.skip_scenarios:
                    ok = _kill_revive(session, fleet, args) and ok
                    ok = _ab_attempts(session, fleet, args) and ok
        finally:
            fleet.stop()
            metrics.reset_brownout()
    print(f"chaos campaign: {'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

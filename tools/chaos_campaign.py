#!/usr/bin/env python
"""Seeded chaos campaign over a live in-process serving fleet.

Drives the full stack — Client -> FederationRouter -> replica
ConnectServers -> scheduler -> engine — through randomized multi-point
fault schedules (spark_tpu/chaos.py), asserting the fleet-grade
resilience contract on every one: byte-identical-or-typed-error, zero
hangs, retry attempts bounded by the unified budget, and the HBM
invariant ``execution + storage <= budget``. Also runs two directed
scenarios the random sweep can't guarantee to hit:

- **kill-one-replica**: stop a replica's HTTP server mid-campaign,
  watch its circuit breaker open on the dispatch failure, revive the
  replica on the SAME port, and assert the breaker walks
  open -> half_open -> closed as the probe request succeeds.
- **A/B attempts**: the same fault-heavy schedule with the unified
  retry budget DISABLED (legacy multiplicative per-layer caps) vs
  ENABLED, comparing total attempt draws.

Usage:
  python tools/chaos_campaign.py --seed 7 --schedules 25
  python tools/chaos_campaign.py --replay /tmp/chaos_fail.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import pyarrow as pa  # noqa: E402
import pyarrow.parquet as pq  # noqa: E402

from spark_tpu import chaos, faults, metrics  # noqa: E402
from spark_tpu import recovery  # noqa: E402
from spark_tpu.connect.server import Client, ConnectServer  # noqa: E402
from spark_tpu.serve.router import serve_fleet  # noqa: E402

#: the mixed workload: scan+filter, aggregation, and a join — together
#: they cross every engine-side injection point the campaign arms
_QUERIES = (
    "SELECT a, b FROM t WHERE a >= 8",
    "SELECT a % 4 AS g, SUM(b) AS s, COUNT(*) AS n FROM t "
    "GROUP BY a % 4",
    "SELECT t.a, t.b, u.c FROM t JOIN u ON t.a = u.a WHERE u.c < 40",
)


def _make_session(tmp):
    from spark_tpu.api.session import SparkSession

    sess = SparkSession.builder.getOrCreate()
    t = pa.table({"a": list(range(96)),
                  "b": [float(i) * 0.5 for i in range(96)]})
    u = pa.table({"a": list(range(0, 96, 2)),
                  "c": [i % 48 for i in range(48)]})
    pt, pu = os.path.join(tmp, "t.parquet"), os.path.join(
        tmp, "u.parquet")
    pq.write_table(t, pt)
    pq.write_table(u, pu)
    sess.read.parquet(pt).createOrReplaceTempView("t")
    sess.read.parquet(pu).createOrReplaceTempView("u")
    return sess


def _result_bytes(table: pa.Table) -> bytes:
    return json.dumps(table.to_pydict(), sort_keys=True).encode()


def _workload(session, url: str, timeout: float):
    """One campaign iteration: all queries through a FRESH client (no
    carried affinity) against the fleet; returns concatenated
    deterministic bytes."""
    rc = getattr(session, "serve_result_cache", None)
    if rc is not None:
        rc.clear()  # faults must reach the engine, not a cached blob
    client = Client(url, timeout=timeout, retries=3)
    out = []
    for q in _QUERIES:
        out.append(_result_bytes(client.sql(q)))
    return b"\x00".join(out)


def _campaign(session, fleet, args) -> bool:
    conf = session.conf
    clean = _workload(session, fleet.url, args.timeout)
    # serve-tier points need the fleet; engine points fire inside the
    # replicas — arm everything
    schedules = chaos.generate_campaign(args.seed, args.schedules)
    print(f"chaos campaign: seed={args.seed} "
          f"schedules={args.schedules}")
    report = chaos.run_campaign(
        conf, lambda: _workload(session, fleet.url, args.timeout),
        schedules, clean_bytes=clean, alarm_s=args.alarm,
        queries=len(_QUERIES),
        memory_manager=session.memory_manager,
        artifact_path=args.artifact, log=print)
    print(json.dumps(report.summary(), indent=2))
    return report.ok


def _replay(session, fleet, args) -> bool:
    sch = chaos.replay_artifact(args.replay)
    print(f"replaying schedule #{sch.index} "
          f"(campaign seed {sch.campaign_seed}): {sch.describe()}")
    clean = _workload(session, fleet.url, args.timeout)
    r = chaos.run_schedule(
        session.conf,
        lambda: _workload(session, fleet.url, args.timeout),
        sch, clean_bytes=clean, alarm_s=args.alarm,
        queries=len(_QUERIES),
        memory_manager=session.memory_manager)
    print(json.dumps(r.to_dict(), indent=2))
    return r.ok


def _kill_revive(session, fleet, args) -> bool:
    """Directed breaker scenario: kill -> open -> revive ->
    half_open -> closed."""
    conf = session.conf
    fed = fleet.router.federation
    conf.set("spark.tpu.serve.breaker.minRequests", 1)
    conf.set("spark.tpu.serve.breaker.openSeconds", 0.3)
    # throttle background health probes: otherwise the router's /health
    # check notices the death first and sidelines the replica before a
    # dispatch ever fails against it, so the breaker never trips. The
    # scenario drives probes explicitly with probe(force=True).
    conf.set("spark.tpu.serve.healthProbeSeconds", 3600.0)
    try:
        # the random sweep may have left stale unhealthy flags and a
        # success-heavy breaker window from injected dispatch faults;
        # re-probe and reset so this scenario starts from a live fleet
        # with empty windows (one failure must reach failureRate)
        fed.probe(force=True)
        for r in fed.replicas:
            r.breaker.reset()
        client = Client(fleet.url, timeout=args.timeout, retries=3)
        _result_bytes(client.sql(_QUERIES[0]))
        victim_id = client.affinity
        victim = next(s for s in fleet.replicas
                      if s.replica_id == victim_id)
        rep = next(r for r in fed.replicas if r.id == victim_id)
        host, port = victim.host, victim.port
        print(f"kill-revive: stopping replica {victim_id} "
              f"({host}:{port})")
        victim.stop()
        # the affinity-routed request hits the dead replica, fails,
        # re-dispatches, and the breaker opens (minRequests=1)
        _result_bytes(client.sql(_QUERIES[1]))
        state = rep.breaker.state
        print(f"  after dispatch failure: breaker={state}")
        if state != "open":
            print("  FAIL: breaker did not open")
            return False
        revived = ConnectServer(session, host=host, port=port,
                                replica_id=victim_id).start()
        fleet.replicas.append(revived)
        time.sleep(0.35)  # let openSeconds elapse
        fed.probe(force=True)  # router sees the replica alive again
        deadline_t = time.time() + 10.0
        transitions = []
        while rep.breaker.state != "closed" \
                and time.time() < deadline_t:
            client.affinity = victim_id  # aim the probe at it
            _result_bytes(client.sql(_QUERIES[0]))
            time.sleep(0.05)
        transitions = [(a, b) for _, a, b in rep.breaker.state_changes]
        print(f"  transitions: {transitions}")
        ok = (("closed", "open") in transitions
              and ("open", "half_open") in transitions
              and ("half_open", "closed") in transitions
              and rep.breaker.state == "closed")
        print(f"  kill-revive: {'ok' if ok else 'FAIL'} "
              f"(final={rep.breaker.state})")
        return ok
    finally:
        conf.unset("spark.tpu.serve.breaker.minRequests")
        conf.unset("spark.tpu.serve.breaker.openSeconds")
        conf.unset("spark.tpu.serve.healthProbeSeconds")


def _ab_attempts(session, fleet, args) -> bool:
    """Same fault-heavy schedule, legacy vs budgeted retry
    accounting."""
    conf = session.conf
    fed = fleet.router.federation
    spec = f"prob:0.4:{args.seed}:transient"
    counts = {}
    for label, enabled in (("legacy", False), ("budgeted", True)):
        # the previous leg's injected dispatch faults leave replicas
        # flagged unhealthy; start each leg from a live fleet so both
        # sides exercise the same dispatch path
        fed.probe(force=True)
        for r in fed.replicas:
            r.breaker.reset()
        conf.set("spark.tpu.recovery.retryBudget.enabled", enabled)
        conf.set("spark.tpu.faultInjection.serve.dispatch", spec)
        conf.set("spark.tpu.faultInjection.execute.device", spec)
        faults.reset(conf)
        before = metrics.retry_budget_stats()
        try:
            for _ in range(3):
                try:
                    _workload(session, fleet.url, args.timeout)
                except Exception:
                    pass  # typed failures are fine; counting attempts
        finally:
            conf.unset("spark.tpu.faultInjection.serve.dispatch")
            conf.unset("spark.tpu.faultInjection.execute.device")
            conf.unset("spark.tpu.recovery.retryBudget.enabled")
            faults.reset(conf)
        after = metrics.retry_budget_stats()
        if enabled:
            counts[label] = (after["draws"] - before["draws"]
                             + after["floor_draws"]
                             - before["floor_draws"])
        else:
            counts[label] = (after["legacy_attempts"]
                             - before["legacy_attempts"])
    budget = int(conf.get(recovery.RETRY_BUDGET_ATTEMPTS))
    cap = 3 * len(_QUERIES) * budget
    ok = counts["budgeted"] <= cap
    print(f"A/B attempts: legacy={counts['legacy']} "
          f"budgeted={counts['budgeted']} "
          f"(cap {cap}: 3 iters x {len(_QUERIES)} queries x "
          f"{budget} budget) -> {'ok' if ok else 'FAIL'}")
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--schedules", type=int, default=25)
    ap.add_argument("--alarm", type=float, default=90.0,
                    help="per-schedule wall-clock bound (zero-hang)")
    ap.add_argument("--timeout", type=float, default=30.0,
                    help="client per-request timeout (mints the "
                         "propagated deadline)")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--artifact",
                    default=os.path.join(tempfile.gettempdir(),
                                         "chaos_fail.json"),
                    help="replayable JSON written on first failure")
    ap.add_argument("--replay", default=None,
                    help="re-run one failing schedule from artifact")
    ap.add_argument("--skip-scenarios", action="store_true",
                    help="random sweep only (no kill-revive / A/B)")
    args = ap.parse_args(argv)

    with tempfile.TemporaryDirectory() as tmp:
        session = _make_session(tmp)
        fleet = serve_fleet(session, replicas=args.replicas)
        try:
            if args.replay:
                ok = _replay(session, fleet, args)
            else:
                ok = _campaign(session, fleet, args)
                if not args.skip_scenarios:
                    ok = _kill_revive(session, fleet, args) and ok
                    ok = _ab_attempts(session, fleet, args) and ok
        finally:
            fleet.stop()
            metrics.reset_brownout()
    print(f"chaos campaign: {'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""AST linter for spark_tpu codebase invariants.

Seven rules the engine relies on but Python cannot enforce:

1. **conf-keys** — every string key passed to ``conf.get(...)`` /
   ``conf.set(...)`` (and builder ``.config(...)``) that looks like a
   config key (``spark.`` / ``spark_tpu.`` prefix) must be a registered
   ConfigEntry or match a registered prefix (conf.register_prefix).
   Unregistered keys silently read as KeyError at runtime and dodge the
   analysis-level gate.

2. **fault-points** — every string literal passed to
   ``faults.inject("<point>", ...)`` must be one of ``faults.POINTS``;
   a typo'd point would make a fault-injection site unreachable while
   tests believe it is covered.

6. **span-names** — every string literal passed to
   ``trace.span("<name>", ...)`` must be declared in
   ``spark_tpu.trace.SPAN_NAMES`` (same discipline as conf keys and
   fault points); an undeclared span name fragments the waterfall and
   the host/device attribution that key off the registry.

3. **fingerprint-purity** — functions on the structural-fingerprint
   path (compile/store.py and planner._stable_adaptive_snapshot) must
   not call ``hash()`` or ``id()`` (process-seeded / address-based:
   both break cross-session executable reuse) and must not iterate a
   dict's ``.items()/.keys()/.values()`` unless wrapped in
   ``sorted(...)`` (dict order is insertion order — a semantically
   equal plan built in a different order would fingerprint
   differently).

4. **metrics-lock** — in spark_tpu/metrics.py every mutation of the
   module-level state (_EVENTS, _GAUGES, _COMPILE_CACHE, ...) must be
   lexically inside ``with _LOCK:`` (``_PATH_CACHE`` under
   ``_IO_LOCK``); the concurrent scheduler serves queries from many
   threads and an unlocked append corrupts the ring.

5. **dead-fault-points** — the converse of rule 2: every point
   declared in ``faults.POINTS`` must have at least one
   ``faults.inject("<point>", ...)`` call site under the linted
   paths. A declared-but-never-injected point registers a conf key
   and documents a recovery seam that does not exist — fault suites
   arming it would silently test nothing.

7. **retry-budget** — every bounded retry loop (a ``for ... in
   range(...)`` whose target or bound names attempts/retries) must
   draw from the unified per-query retry budget: the enclosing
   function has to reference ``recovery.retry_allowed`` /
   ``RetryBudget`` / ``.draw(...)``. A loop that retries on its own
   private counter multiplies with every other layer's counter —
   exactly the attempt amplification the unified budget exists to
   kill. Exemptions: ``retry_loop_allow = ["path.py:function"]`` in
   ``[tool.lint-invariants]``.

Run as a CLI (exit 0 clean / 1 findings) or import ``run_lint()``;
tests/test_analysis.py runs it as a test so CI enforces it. Optional
overrides live in ``[tool.lint-invariants]`` in pyproject.toml.
"""

from __future__ import annotations

import ast
import os
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: defaults; [tool.lint-invariants] in pyproject.toml may override
DEFAULT_CONFIG = {
    "paths": ["spark_tpu"],
    "key_prefixes": ["spark.", "spark_tpu."],
    # file -> functions on the fingerprint path ([] = every function)
    "fingerprint_paths": {
        os.path.join("spark_tpu", "compile", "store.py"): [],
        os.path.join("spark_tpu", "physical", "planner.py"):
            ["_stable_adaptive_snapshot"],
    },
    "locked_modules": [os.path.join("spark_tpu", "metrics.py")],
    # module state -> lock that must guard its mutations
    "lock_map": {"_PATH_CACHE": "_IO_LOCK", "_LOG_BUF": "_IO_LOCK",
                 "_LOG_BUF_PATH": "_IO_LOCK",
                 "_LOG_LAST_FLUSH": "_IO_LOCK"},
    "default_lock": "_LOCK",
    # "path.py:function" entries exempt from rule 7 (retry-budget);
    # recovery.py itself IMPLEMENTS the budget so its own draw loop
    # is the mechanism, not a violator
    "retry_loop_allow": [],
}


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _load_config() -> dict:
    cfg = {k: v for k, v in DEFAULT_CONFIG.items()}
    pyproject = os.path.join(REPO_ROOT, "pyproject.toml")
    try:
        import tomllib
    except ImportError:  # py<3.11: tomli is API-compatible
        try:
            import tomli as tomllib
        except ImportError:
            return cfg
    try:
        with open(pyproject, "rb") as f:
            data = tomllib.load(f)
    except OSError:
        return cfg
    user = data.get("tool", {}).get("lint-invariants", {})
    for k in ("paths", "key_prefixes", "locked_modules",
              "retry_loop_allow"):
        if k in user:
            cfg[k] = list(user[k])
    return cfg


def _iter_py_files(cfg: dict):
    for rel in cfg["paths"]:
        base = os.path.join(REPO_ROOT, rel)
        if os.path.isfile(base):
            yield base
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# ---- rule 1: conf keys ------------------------------------------------------


def _check_conf_keys(tree: ast.AST, rel: str, cfg: dict,
                     out: List[Finding]) -> None:
    from spark_tpu import conf as CF

    prefixes = tuple(cfg["key_prefixes"])
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("get", "set", "config")
                and node.args):
            continue
        key = _const_str(node.args[0])
        if key is None or not key.startswith(prefixes):
            continue
        if not CF.is_registered(key):
            out.append(Finding(
                "conf-keys", rel, node.lineno,
                f"config key {key!r} is not a registered ConfigEntry "
                "or prefix (register it in spark_tpu/conf.py)"))


# ---- rule 2: fault points ---------------------------------------------------


def _check_fault_points(tree: ast.AST, rel: str, out: List[Finding],
                        seen: Optional[Set[str]] = None) -> None:
    from spark_tpu import faults

    valid: Set[str] = set(faults.POINTS)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else \
            fn.id if isinstance(fn, ast.Name) else None
        if name != "inject":
            continue
        point = _const_str(node.args[0])
        if point is None:
            continue
        if point not in valid:
            out.append(Finding(
                "fault-points", rel, node.lineno,
                f"fault point {point!r} is not in faults.POINTS — "
                "this injection site can never fire"))
        elif seen is not None:
            seen.add(point)


def _check_dead_fault_points(seen: Set[str],
                             out: List[Finding]) -> None:
    """Rule 5: every declared point must be injectable somewhere."""
    from spark_tpu import faults

    for point in sorted(set(faults.POINTS) - seen):
        out.append(Finding(
            "dead-fault-points",
            os.path.join("spark_tpu", "faults.py"), 0,
            f"fault point {point!r} is declared in faults.POINTS but "
            "has no faults.inject(...) call site under the linted "
            "paths — arming it would silently test nothing"))


# ---- rule 6: span names -----------------------------------------------------


def _check_span_names(tree: ast.AST, rel: str,
                      out: List[Finding]) -> None:
    """Every literal span name opened via ``trace.span("<name>", ...)``
    (or a bare imported ``span("<name>", ...)``) must be declared in
    the central ``spark_tpu.trace.SPAN_NAMES`` registry."""
    from spark_tpu import trace

    valid: Set[str] = set(trace.SPAN_NAMES)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute):
            base = fn.value
            if not (fn.attr == "span" and isinstance(base, ast.Name)
                    and base.id in ("trace", "_trace")):
                continue
        elif isinstance(fn, ast.Name) and fn.id == "span":
            pass
        else:
            continue
        name = _const_str(node.args[0])
        if name is not None and name not in valid:
            out.append(Finding(
                "span-names", rel, node.lineno,
                f"span name {name!r} is not declared in "
                "spark_tpu.trace.SPAN_NAMES — register it so the "
                "waterfall/attribution rollups see it"))


# ---- rule 7: bounded retry loops draw from the unified budget ---------------

#: a loop is retry-shaped when its target or range bound names one of
#: these (``for attempt in range(retries + 1)`` and friends)
_RETRY_HINTS = ("attempt", "retry", "retries")

#: the enclosing function satisfies the rule by referencing any of the
#: unified-budget API surface
_BUDGET_MARKERS = ("retry_allowed", "RetryBudget", "draw",
                   "retry_budget", "bind_budget")


def _check_retry_budget(tree: ast.AST, rel: str, cfg: dict,
                        out: List[Finding]) -> None:
    allow = set(cfg.get("retry_loop_allow", []))

    def _hinted(name: str) -> bool:
        low = name.lower()
        return any(h in low for h in _RETRY_HINTS)

    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        draws = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) \
                    and node.id in _BUDGET_MARKERS:
                draws = True
                break
            if isinstance(node, ast.Attribute) \
                    and node.attr in _BUDGET_MARKERS:
                draws = True
                break
        if draws or f"{rel}:{fn.name}" in allow:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.For):
                continue
            it = node.iter
            if not (isinstance(it, ast.Call)
                    and isinstance(it.func, ast.Name)
                    and it.func.id == "range"):
                continue
            tgt = node.target
            shaped = isinstance(tgt, ast.Name) and _hinted(tgt.id)
            if not shaped:
                for sub in ast.walk(it):
                    nm = sub.id if isinstance(sub, ast.Name) else \
                        sub.attr if isinstance(sub, ast.Attribute) \
                        else None
                    if nm is not None and _hinted(nm):
                        shaped = True
                        break
            if shaped:
                out.append(Finding(
                    "retry-budget", rel, node.lineno,
                    f"retry loop in {fn.name}() never draws from the "
                    "unified RetryBudget (recovery.retry_allowed / "
                    "budget.draw) — a private attempt counter "
                    "multiplies with every other layer's; exempt via "
                    "retry_loop_allow in [tool.lint-invariants] only "
                    "if the loop genuinely is not a retry"))


# ---- rule 3: fingerprint purity ---------------------------------------------


def _check_fingerprint_purity(tree: ast.AST, rel: str,
                              only_functions: List[str],
                              out: List[Finding]) -> None:
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if only_functions and fn.name not in only_functions:
            continue
        sorted_spans: List[Tuple[int, int]] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id == "sorted":
                sorted_spans.append(
                    (node.lineno, node.end_lineno or node.lineno))
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in ("hash", "id"):
                out.append(Finding(
                    "fingerprint-purity", rel, node.lineno,
                    f"{node.func.id}() inside fingerprint function "
                    f"{fn.name}(): process-seeded/address-based values "
                    "break cross-session executable reuse"))
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("items", "keys", "values") \
                    and not node.args:
                inside_sorted = any(
                    lo <= node.lineno <= hi for lo, hi in sorted_spans)
                if not inside_sorted:
                    out.append(Finding(
                        "fingerprint-purity", rel, node.lineno,
                        f".{node.func.attr}() iteration inside "
                        f"fingerprint function {fn.name}() is dict-"
                        "order-dependent; wrap in sorted(...)"))


# ---- rule 4: metrics mutations under the lock -------------------------------

_MUTATORS = ("append", "pop", "popleft", "clear", "update", "extend",
             "setdefault", "insert", "remove")


def _check_metrics_locks(tree: ast.AST, rel: str, cfg: dict,
                         out: List[Finding]) -> None:
    module_state: Set[str] = set()
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id.startswith("_"):
                module_state.add(t.id)
    locks = {cfg["default_lock"]} | set(cfg["lock_map"].values())
    module_state -= locks

    def required_lock(name: str) -> str:
        return cfg["lock_map"].get(name, cfg["default_lock"])

    def base_name(node: ast.AST) -> Optional[str]:
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            node = node.value
        if isinstance(node, ast.Name):
            return node.id
        return None

    def walk(node: ast.AST, held: Set[str], depth: int) -> None:
        if isinstance(node, ast.With):
            got = set(held)
            for item in node.items:
                n = base_name(item.context_expr)
                if n in locks:
                    got.add(n)
            for child in node.body:
                walk(child, got, depth)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for child in node.body:
                walk(child, set(), depth + 1)
            return

        mutated: List[Tuple[str, int]] = []
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else getattr(node, "targets", None) or [node.target]
            for t in targets:
                n = base_name(t)
                if n in module_state:
                    if depth > 0 or not isinstance(t, ast.Name):
                        mutated.append((n, node.lineno))
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in _MUTATORS:
                n = base_name(sub.func.value)
                if n in module_state and depth > 0:
                    mutated.append((n, sub.lineno))
        for name, line in mutated:
            need = required_lock(name)
            # the recursive walk revisits nested statements; report
            # each (state, line) once
            if need not in held and (name, line) not in reported:
                reported.add((name, line))
                out.append(Finding(
                    "metrics-lock", rel, line,
                    f"mutation of {name} outside `with {need}:` — "
                    "the concurrent scheduler mutates metrics from "
                    "many threads"))
        for child in ast.iter_child_nodes(node):
            walk(child, held, depth)

    reported: Set[Tuple[str, int]] = set()
    for top in tree.body:
        walk(top, set(), 0)


# ---- driver -----------------------------------------------------------------


def _import_all_modules() -> None:
    """ConfigEntry / fault-point registration happens at import time of
    whichever module owns the entry (recovery.py registers
    spark.checkpoint.dir, ...), so the ground-truth registry is only
    complete once every spark_tpu module is imported. Failures are
    tolerated per-module (optional deps may be stubbed out)."""
    import importlib
    import pkgutil

    import spark_tpu

    for info in pkgutil.walk_packages(spark_tpu.__path__,
                                      prefix="spark_tpu."):
        try:
            importlib.import_module(info.name)
        except Exception:
            pass


def run_lint(config: Optional[dict] = None) -> List[Finding]:
    sys.path.insert(0, REPO_ROOT)
    cfg = config or _load_config()
    _import_all_modules()
    findings: List[Finding] = []
    fingerprint: Dict[str, List[str]] = dict(cfg["fingerprint_paths"])
    locked = set(cfg["locked_modules"])
    injected_points: Set[str] = set()
    for path in _iter_py_files(cfg):
        rel = os.path.relpath(path, REPO_ROOT)
        with open(path, "r") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError as e:
            findings.append(Finding("parse", rel, e.lineno or 0,
                                    f"syntax error: {e.msg}"))
            continue
        _check_conf_keys(tree, rel, cfg, findings)
        _check_fault_points(tree, rel, findings, injected_points)
        _check_span_names(tree, rel, findings)
        _check_retry_budget(tree, rel, cfg, findings)
        if rel in fingerprint:
            _check_fingerprint_purity(tree, rel, fingerprint[rel],
                                      findings)
        if rel in locked:
            _check_metrics_locks(tree, rel, cfg, findings)
    _check_dead_fault_points(injected_points, findings)
    return findings


def main(argv: Optional[List[str]] = None) -> int:
    findings = run_lint()
    for f in findings:
        print(f.format())
    n = len(findings)
    print(f"lint_invariants: {n} finding(s)"
          if n else "lint_invariants: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

#!/usr/bin/env python
"""Tree-wide concurrency linter: static lock-order / shared-state /
blocking-call verification against the lock-hierarchy registry
(spark_tpu/locks.py).

Companion to tools/lint_invariants.py in the tier-1 flow; the analysis
itself lives in spark_tpu/analysis/concurrency.py so tests and the
engine can import it. Rules (stable Diagnostic codes):

- CONC-ORDER-CYCLE   lock-acquisition edge inverting locks.LOCK_RANKS,
                     or a cycle among unranked locks
- CONC-UNLOCKED-MUT  shared state mutated under a lock somewhere but
                     bare elsewhere
- CONC-BLOCKING-HELD blocking call (queue/HTTP/file IO/subprocess/
                     sleep/device sync) while holding a lock
- CONC-WAIT-NOLOOP   Condition.wait outside a predicate loop

Exemptions live in ``[tool.lint-concurrency]`` in pyproject.toml and
MUST carry a non-empty justification string; an empty justification or
a stale key (matching nothing in the tree) is itself a finding, so the
table can never silently rot.

Exit 0 when clean, 1 otherwise.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

DEFAULT_CONFIG: Dict[str, object] = {
    "paths": ["spark_tpu"],
    #: files the analyzer must not scan: locks.py IS the proxy layer
    #: (its acquire/release would read as self-nesting)
    "exclude": ["spark_tpu/locks.py"],
    #: lock-variable aliases: bindings the AST cannot see through
    #: (assignment of another object's lock)
    "aliases": {},
    #: "<rel>::<Class>._attr" / "<rel>::_VAR" -> justification
    "exempt_unlocked": {},
    #: "<rel>::<qualname>" -> justification
    "exempt_blocking": {},
}


def _load_config() -> Dict[str, object]:
    """DEFAULT_CONFIG merged with ``[tool.lint-concurrency]`` from
    pyproject.toml (sub-tables ``aliases`` / ``exempt-unlocked`` /
    ``exempt-blocking``)."""
    cfg = {k: (dict(v) if isinstance(v, dict) else list(v))
           for k, v in DEFAULT_CONFIG.items()}
    try:
        import tomllib
    except ImportError:  # py<3.11: tomli is API-compatible
        try:
            import tomli as tomllib
        except ImportError:  # pragma: no cover
            return cfg
    path = os.path.join(REPO_ROOT, "pyproject.toml")
    try:
        with open(path, "rb") as f:
            data = tomllib.load(f)
    except FileNotFoundError:  # pragma: no cover
        return cfg
    section = data.get("tool", {}).get("lint-concurrency", {})
    for key in ("paths", "exclude"):
        if key in section:
            cfg[key] = list(section[key])
    for toml_key, cfg_key in (("aliases", "aliases"),
                              ("exempt-unlocked", "exempt_unlocked"),
                              ("exempt-blocking", "exempt_blocking")):
        if toml_key in section:
            cfg[cfg_key] = dict(section[toml_key])
    return cfg


def _iter_py_files(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        root = os.path.join(REPO_ROOT, p)
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def _exemption_findings(cfg, diagnostics_module) -> List:
    """Typed findings for malformed exemption tables: every entry must
    carry a non-empty justification, and every key must still match
    something scannable (a stale key means the code it excused is gone
    or moved — the table must follow)."""
    from spark_tpu.analysis.diagnostics import Diagnostic

    out = []
    known_rels = set()
    for path in _iter_py_files(list(cfg["paths"])):
        known_rels.add(os.path.relpath(path, REPO_ROOT))
    for table, name in ((cfg["exempt_unlocked"], "exempt-unlocked"),
                        (cfg["exempt_blocking"], "exempt-blocking"),
                        (cfg["aliases"], "aliases")):
        for key, justification in table.items():
            if not str(justification).strip():
                out.append(Diagnostic(
                    code="CONC-EXEMPT-UNJUSTIFIED", level="error",
                    node=f"pyproject.toml [{name}]",
                    message=f"exemption {key!r} has no justification",
                    hint="every exemption must say WHY it is safe"))
            rel = key.split("::", 1)[0]
            if rel not in known_rels:
                out.append(Diagnostic(
                    code="CONC-EXEMPT-STALE", level="error",
                    node=f"pyproject.toml [{name}]",
                    message=f"exemption {key!r} references "
                            f"{rel}, which is not in the scanned tree",
                    hint="delete or update the stale entry"))
    return out


def run_lint(config=None) -> List:
    """All findings over the configured tree; importable for tests."""
    from spark_tpu.analysis import concurrency

    cfg = config if config is not None else _load_config()
    exclude = set(cfg.get("exclude", []))
    sources: Dict[str, str] = {}
    for path in _iter_py_files(list(cfg["paths"])):
        rel = os.path.relpath(path, REPO_ROOT)
        if rel in exclude:
            continue
        with open(path, encoding="utf-8") as f:
            sources[rel] = f.read()
    findings = concurrency.analyze_sources(
        sources,
        aliases=dict(cfg.get("aliases", {})),
        exempt_unlocked=dict(cfg.get("exempt_unlocked", {})),
        exempt_blocking=dict(cfg.get("exempt_blocking", {})))
    findings.extend(_exemption_findings(cfg, None))
    return findings


def main() -> int:
    findings = run_lint()
    for d in findings:
        print(d.format())
    if findings:
        print(f"lint_concurrency: {len(findings)} finding(s)")
        return 1
    print("lint_concurrency: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
